"""`mx.tune`: measured-trial autotuner over the framework's knob space.

Closes the loop the observability stack opened (TVM-style, arXiv
1802.04799): the repo's hand-picked performance knobs —
``steps_per_program``, shape buckets, ``MXTPU_PASSES`` subsets, remat
policy, donation, layout, the serve batcher — become a SEARCHED space
instead of documentation burden.  Three pieces:

  * :mod:`~mxtpu.tune.registry` — subsystems declare their tunables
    (name, domain, env var, apply hook); seeded with every knob in
    `docs/env_vars.md`.
  * :mod:`~mxtpu.tune.trial` + :mod:`~mxtpu.tune.search` — measured
    trials through ``bench_common``-speaking benches in subprocesses
    (one bench row per trial, appended to the ``MXTPU_RUN_DIR``
    ledger so `tools/compare_runs.py` and `mx.obs` see tuning
    history), driven by cost-model-seeded successive halving.
  * :mod:`~mxtpu.tune.db` — winning configs persisted per (graph
    fingerprint, backend, batch profile) with atomic writes, and
    **auto-applied** at ``Module.bind`` / ``hybridize`` /
    ``serve.add_model`` when ``MXTPU_TUNE=apply`` — with provenance
    on `mx.inspect` program records and a ``tuning`` telemetry event.

Auto-apply is OFF by default: every hook reduces to one cached check
(:func:`apply_enabled`).  Typical workflow::

    # search (one-off, writes the DB):
    result = mx.tune.tune(
        [sys.executable, "benchmark/python/bench_train_loop.py"],
        symbol=net, profile="b32", max_trials=12)

    # every later run (applies the DB at bind):
    MXTPU_TUNE=apply python train.py
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

from ..base import getenv
from . import db, registry, search as search_mod, trial as trial_mod
from .db import entry_key, lookup, make_entry, store
from .registry import (Knob, apply_config, current_config, declare,
                       defaults, env_for_config, get, knobs, names,
                       validate_config)
from .search import SearchResult, cost_model_priors, search
from .trial import Trial, TrialRunner, objective

__all__ = [
    "Knob", "declare", "get", "knobs", "names", "defaults",
    "current_config", "validate_config", "apply_config",
    "env_for_config",
    "Trial", "TrialRunner", "objective",
    "SearchResult", "search", "cost_model_priors",
    "lookup", "store", "make_entry", "entry_key",
    "mode", "enable", "apply_enabled", "maybe_apply",
    "current_applied", "tune", "fingerprint_of", "profile_of_shapes",
]

_lock = threading.Lock()
_MODE = (getenv("MXTPU_TUNE", "0") or "0").strip().lower()
#: provenance of the last auto-applied DB config in this process
#: (knobs are process-global env, so the ambient string is truthful
#: for every program built after the apply)
_APPLIED: Optional[str] = None
_APPLIED_KEYS: set = set()


def mode() -> str:
    """The tuner mode: ``"apply"`` (DB configs auto-apply at bind) or
    ``"off"``.  From ``MXTPU_TUNE`` at import (``apply``/``1``/``true``
    arm it); flip at runtime with :func:`enable`."""
    return "apply" if _MODE in ("apply", "1", "true") else "off"


def enable(on: Any = "apply") -> None:
    """Flip auto-apply at runtime (tests / embedding).  ``on`` may be
    a mode string or a bool."""
    global _MODE
    if isinstance(on, bool):
        _MODE = "apply" if on else "0"
    else:
        _MODE = str(on).strip().lower()


def apply_enabled() -> bool:
    """The ONE check every bind/hybridize/add_model hook pays when the
    tuner is off (the default)."""
    return _MODE in ("apply", "1", "true")


def current_applied() -> Optional[str]:
    """Provenance string of the auto-applied tuning config active in
    this process (e.g. ``"tune:key=ab12cd34,donate=0"``), or None.
    `mx.inspect.program` stamps this on every program record."""
    return _APPLIED


def fingerprint_of(symbol=None, name: Optional[str] = None) -> str:
    """The graph identity a DB entry is keyed on: the name-independent
    :func:`mxtpu.compile_cache.graph_fingerprint` when a symbol is in
    hand, else a literal ``name:...`` key (serve models are registered
    by name before any trace exists)."""
    if symbol is not None:
        from .. import compile_cache as _cc

        return _cc.graph_fingerprint(symbol)
    if name:
        return "name:%s" % name
    raise ValueError("fingerprint_of needs a symbol or a name")


def profile_of_shapes(shapes) -> str:
    """Canonical batch-profile string from bind-style data shapes
    (``[(name, shape), ...]`` pairs or DataDesc tuples):
    ``"data=32x64,label=32"``.  The profile half of the DB key."""
    parts = []
    for d in shapes or []:
        try:
            name, shape = d[0], tuple(d[1])
        except Exception:
            continue
        parts.append("%s=%s" % (name, "x".join(str(int(s))
                                               for s in shape)))
    return ",".join(parts)


def _backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def maybe_apply(symbol=None, name: Optional[str] = None,
                profile: str = "", site: str = "bind") -> Optional[str]:
    """Auto-apply hook: when ``MXTPU_TUNE=apply`` and the tuning DB
    holds an entry for this (graph, backend, profile), install its
    config and return the provenance string; otherwise None.

    Called from ``Module.bind``, ``HybridBlock._build_cache`` and
    ``serve.Server.add_model``.  Off (the default) this is one bool
    check.  A DB miss, an unreadable entry, or a config whose knobs
    have since narrowed their domains all degrade to "no apply" — the
    tuner must never take a bind down."""
    if not apply_enabled():
        return None
    global _APPLIED
    try:
        graph = fingerprint_of(symbol, name)
        backend = _backend()
        entry = db.lookup(graph, backend, profile)
        if entry is None and profile:
            entry = db.lookup(graph, backend, "*")
        if entry is None:
            return None
        key = entry["key"]
        with _lock:
            seen = key in _APPLIED_KEYS
            _APPLIED_KEYS.add(key)
        cfg = registry.apply_config(entry["config"])
        prov = "tune:key=%s,%s" % (
            key[:8], ",".join("%s=%s" % kv for kv in sorted(cfg.items())))
        _APPLIED = prov
        from .. import profiler as _prof
        from .. import telemetry as _tel

        _prof.inc_stat("tune_apply")
        if not seen:
            _tel.record("tuning", action="apply", site=site, key=key,
                        provenance=prov, profile=profile or None,
                        config=json.dumps(cfg, sort_keys=True))
        return prov
    except Exception:
        from .. import profiler as _prof

        _prof.inc_stat("tune_apply_errors")
        return None


def tune(bench_argv: Sequence[str],
         symbol=None, name: Optional[str] = None,
         profile: str = "",
         knob_names: Optional[Sequence[str]] = None,
         max_trials: int = 16,
         run_dir: Optional[str] = None,
         timeout_s: float = 300.0,
         db_dir: Optional[str] = None,
         seed: int = 0,
         store_db: bool = True) -> SearchResult:
    """One full tuning session: measure, search, persist the winner.

    ``bench_argv`` is a ``bench_common``-speaking benchmark command
    (its env decides what it measures — the trial runner injects each
    candidate config).  The winning config (never worse than the
    measured baseline) is stored in the tuning DB under
    (``symbol``/``name`` fingerprint, backend, ``profile``) so later
    processes with ``MXTPU_TUNE=apply`` pick it up at bind.

    The cost model is seeded from the program's ``inspect``
    cost-analysis when a symbol's program is registered, plus the
    baseline trial's phase attribution (see
    :func:`~mxtpu.tune.search.cost_model_priors`).
    """
    from .. import telemetry as _tel

    analysis = None
    if symbol is not None:
        try:
            from .. import inspect as _inspect

            rec = _inspect.find_for_symbol(symbol)
            if rec is not None:
                si = rec.latest_sig()
                if si is not None:
                    analysis = si.analyze()
        except Exception:
            analysis = None
    runner = trial_mod.TrialRunner(bench_argv, run_dir=run_dir,
                                   timeout_s=timeout_s)
    result = search_mod.search(runner, knob_names=knob_names,
                               max_trials=max_trials, seed=seed,
                               analysis=analysis)
    entry_path = None
    if store_db:
        graph = fingerprint_of(symbol, name)
        entry = db.make_entry(
            graph, _backend(), profile, result.config,
            metric=result.score, baseline_metric=result.baseline_score,
            trials=len(result.trials), run_ids=result.run_ids)
        entry_path = db.store(entry, db_dir)
    _tel.record("tuning", action="session",
                trials=len(result.trials), score=result.score,
                baseline=result.baseline_score,
                improved=result.improved,
                config=json.dumps(result.config, sort_keys=True),
                db_path=entry_path)
    return result


def _metrics() -> Dict[str, Any]:
    from .. import profiler as _prof

    stats = _prof.stats()
    return {"mode": mode(), "applied": _APPLIED,
            "trials": stats.get("tune_trials", 0),
            "applies": stats.get("tune_apply", 0)}


def _register_provider() -> None:
    try:
        from .. import telemetry as _tel

        _tel.register_metrics_provider("tune", _metrics)
    except Exception:
        pass


_register_provider()
