"""Persistent tuning DB: graph identity + backend + batch profile -> config.

Keyed like the compile cache: a sha256 over a canonical JSON header
(``mxtpu-tune-v1`` schema, name-independent graph fingerprint from
:func:`mxtpu.compile_cache.graph_fingerprint`, the jax backend, and a
batch-profile string), so two processes that bind the same
architecture at the same batch geometry resolve the same entry file
even though gluon auto-uniquifies node names per process.

One entry per key, one JSON file per entry, written with
``resilience.atomic_write`` (temp + fsync + rename) so a reader never
observes a torn entry; garbage files are treated as cache misses, not
errors — a tuning DB must never take a training job down.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

SCHEMA = "mxtpu-tune-v1"

__all__ = ["SCHEMA", "db_dir", "entry_key", "store", "lookup",
           "entries", "make_entry"]


def db_dir(path: Optional[str] = None) -> str:
    """Resolve the DB directory: explicit arg > ``MXTPU_TUNE_DB`` env
    > ``~/.cache/mxtpu/tune_db`` (mirrors the compile-cache default)."""
    d = path or os.environ.get("MXTPU_TUNE_DB") \
        or os.path.join(os.path.expanduser("~"), ".cache", "mxtpu",
                        "tune_db")
    return d


def entry_key(graph: str, backend: str, profile: str) -> str:
    """Stable content key: sha256 over the canonical key header."""
    header = json.dumps(
        {"schema": SCHEMA, "graph": graph, "backend": backend,
         "profile": profile},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(header.encode("utf-8")).hexdigest()


def make_entry(graph: str, backend: str, profile: str,
               config: Dict[str, str],
               metric: Optional[float] = None,
               baseline_metric: Optional[float] = None,
               trials: int = 0,
               run_ids: Optional[List[str]] = None,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    entry = {
        "schema": SCHEMA,
        "key": entry_key(graph, backend, profile),
        "graph": graph,
        "backend": backend,
        "profile": profile,
        "config": dict(config),
        "metric": metric,
        "baseline_metric": baseline_metric,
        "trials": trials,
        "run_ids": list(run_ids or []),
        "ts": time.time(),
    }
    if extra:
        entry["extra"] = dict(extra)
    return entry


def _entry_path(directory: str, key: str) -> str:
    return os.path.join(directory, key + ".json")


def store(entry: Dict[str, Any],
          directory: Optional[str] = None) -> str:
    """Atomically persist ``entry`` under its key; returns the path."""
    from ..resilience import atomic_write

    d = db_dir(directory)
    os.makedirs(d, exist_ok=True)
    path = _entry_path(d, entry["key"])
    data = json.dumps(entry, sort_keys=True, indent=1,
                      default=str).encode("utf-8")
    with atomic_write(path, mode="wb") as f:
        f.write(data)
    return path


def lookup(graph: str, backend: str, profile: str,
           directory: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The stored entry for this (graph, backend, profile), or None.

    Torn/garbage entry files read as a miss: the DB is advisory."""
    path = _entry_path(db_dir(directory),
                       entry_key(graph, backend, profile))
    try:
        with open(path, "r", encoding="utf-8") as f:
            entry = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(entry, dict) or entry.get("schema") != SCHEMA \
            or not isinstance(entry.get("config"), dict):
        return None
    return entry


def entries(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every readable entry in the DB (skipping garbage), newest first."""
    d = db_dir(directory)
    out = []
    try:
        names = [n for n in os.listdir(d) if n.endswith(".json")]
    except OSError:
        return out
    for name in names:
        try:
            with open(os.path.join(d, name), "r",
                      encoding="utf-8") as f:
                entry = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(entry, dict) and entry.get("schema") == SCHEMA:
            out.append(entry)
    out.sort(key=lambda e: e.get("ts") or 0, reverse=True)
    return out
