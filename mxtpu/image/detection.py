"""Detection image iterator (reference: `python/mxnet/image/detection.py`).

Labels are per-object rows `[class, xmin, ymin, xmax, ymax, ...]` with a
2-element header (objects start after `label[0]` header words), padded to
a fixed number of objects per image — the reference's det-recordio
convention.  Geometric augmenters transform boxes together with pixels.
"""
from __future__ import annotations

import random as pyrandom
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from ..ndarray import ndarray as nd_mod
from . import image as img_mod

__all__ = ["DetAugmenter", "DetHorizontalFlipAug", "DetBorrowAug",
           "DetRandomSelectAug", "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter(object):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a pixel-only augmenter (no geometry change)."""

    def __init__(self, augmenter):
        super().__init__()
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            arr = img_mod._to_np(src)[:, ::-1].copy()
            label = label.copy()
            valid = label[:, 0] >= 0
            xmin = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - xmin
            return img_mod._to_nd(arr), label
        return src, label


class DetRandomSelectAug(DetAugmenter):
    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() >= self.skip_prob and self.aug_list:
            return pyrandom.choice(self.aug_list)(src, label)
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       inter_method=2, **kwargs):
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(img_mod.ResizeAug(resize, inter_method)))
    auglist.append(DetBorrowAug(img_mod.ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(img_mod.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(img_mod.ColorJitterAug(
            brightness, contrast, saturation)))
    if mean is not None or std is not None:
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(img_mod.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(img_mod.ImageIter):
    """Detection iterator (reference `detection.py:ImageDetIter`)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        aug = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_mirror", "mean", "std",
                         "brightness", "contrast", "saturation",
                         "inter_method")})
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name,
                         **{k: v for k, v in kwargs.items()
                            if k in ("shuffle", "part_index", "num_parts",
                                     "path_imgidx", "dtype")})
        self.det_auglist = aug
        self.max_objects = int(kwargs.get("max_objects", 13))
        self.label_shape = (self.max_objects, 5)

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self.label_shape)]

    def _parse_label(self, label) -> np.ndarray:
        """Flat det label -> [N,5] object rows (reference
        `detection.py:_parse_label`)."""
        raw = np.asarray(label, np.float32).ravel()
        if raw.size < 7:
            raise MXNetError("label too short for detection: %d" % raw.size)
        header_width = int(raw[0])
        obj_width = int(raw[1])
        body = raw[header_width:]
        n = body.size // obj_width
        return body[:n * obj_width].reshape(n, obj_width)[:, :5]

    def next(self) -> DataBatch:
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.full((self.batch_size,) + self.label_shape, -1.0,
                              np.float32)
        i = 0
        while i < self.batch_size:
            try:
                label, s = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                break
            img = img_mod.imdecode(s, flag=1 if c == 3 else 0)
            objs = self._parse_label(label)
            for aug in self.det_auglist:
                img, objs = aug(img, objs)
            arr = img_mod._to_np(img).astype(np.float32)
            if arr.shape[:2] != (h, w):
                arr = img_mod._to_np(img_mod.imresize(arr, w, h))
            batch_data[i] = arr.transpose(2, 0, 1)
            n = min(len(objs), self.max_objects)
            batch_label[i, :n] = objs[:n]
            i += 1
        return DataBatch(data=[nd_mod.array(batch_data)],
                        label=[nd_mod.array(batch_label)],
                        pad=self.batch_size - i,
                        provide_data=self.provide_data,
                        provide_label=self.provide_label)
