"""`mx.image` — image IO/augmentation (reference: `python/mxnet/image/`)."""
from .image import *  # noqa: F401,F403
from .detection import (DetAugmenter, DetHorizontalFlipAug, DetBorrowAug,
                        DetRandomSelectAug, CreateDetAugmenter,
                        ImageDetIter)
