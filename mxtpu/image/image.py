"""Image processing + ImageIter (reference: `python/mxnet/image/image.py`).

The reference backs these with OpenCV ops (`_cvimread`/`_cvimresize`...);
here decode/resize run on host numpy/PIL (IO-side work stays on host —
the TPU consumes the decoded batch), and tensor-valued augmenters operate
on NDArrays so they fuse into the device pipeline when applied there.
"""
from __future__ import annotations

import io as _io
import os
import random as pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..context import cpu
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import ndarray as nd_mod
from ..ndarray.ndarray import NDArray
from .. import recordio

__all__ = ["imread", "imdecode", "imresize", "scale_down", "resize_short",
           "copyMakeBorder", "fixed_crop", "random_crop", "center_crop",
           "color_normalize", "random_size_crop", "Augmenter",
           "SequentialAug", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "RandomOrderAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "HueJitterAug", "ColorJitterAug", "LightingAug",
           "ColorNormalizeAug", "RandomGrayAug", "HorizontalFlipAug",
           "CastAug", "CreateAugmenter", "ImageIter"]


def _to_np(src) -> np.ndarray:
    return src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)


def _to_nd(arr: np.ndarray) -> NDArray:
    return nd_mod.array(arr, dtype=arr.dtype)


def imdecode(buf, to_rgb=1, flag=1, **kwargs) -> NDArray:
    """Decode an image buffer to HWC uint8 (reference `image.py:143`,
    backed by `_cvimdecode`)."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    try:
        from PIL import Image

        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        arr = np.asarray(img, dtype=np.uint8)
        if not flag:
            arr = arr[:, :, None]
    except ImportError:
        arr = np.load(_io.BytesIO(buf), allow_pickle=False)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return _to_nd(arr)


def imread(filename, flag=1, to_rgb=1, **kwargs) -> NDArray:
    with open(filename, "rb") as f:
        return imdecode(f.read(), to_rgb=to_rgb, flag=flag)


def imresize(src, w, h, interp=1) -> NDArray:
    arr = _to_np(src)
    try:
        from PIL import Image

        modes = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                 3: Image.NEAREST, 4: Image.LANCZOS}
        mode = modes.get(interp, Image.BILINEAR)
        if arr.dtype == np.uint8:
            out = np.asarray(Image.fromarray(
                arr if arr.ndim == 3 and arr.shape[2] == 3
                else arr.squeeze()).resize((w, h), mode))
            if out.ndim == 2:
                out = out[:, :, None]
        else:
            # float (post-augmenter) data can be negative or >255 — a
            # uint8 round-trip would clip/wrap it.  Resize each channel
            # in PIL float mode instead.
            if arr.ndim == 2:
                arr = arr[:, :, None]
            chans = [np.asarray(Image.fromarray(
                arr[:, :, c].astype(np.float32), mode="F")
                .resize((w, h), mode)) for c in range(arr.shape[2])]
            out = np.stack(chans, axis=2)
    except ImportError:
        if arr.ndim == 2:
            arr = arr[:, :, None]
        hh, ww = arr.shape[:2]
        ri = (np.arange(h) * hh // h).clip(0, hh - 1)
        ci = (np.arange(w) * ww // w).clip(0, ww - 1)
        out = arr[ri][:, ci]
    return _to_nd(out.astype(arr.dtype))


def scale_down(src_size, size):
    """Scale `size` down to fit in `src_size` keeping aspect (reference
    `image.py:201`)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2) -> NDArray:
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(arr, new_w, new_h, interp=interp)


def copyMakeBorder(src, top, bot, left, right, type=0, values=0) -> NDArray:
    arr = _to_np(src)
    out = np.pad(arr, ((top, bot), (left, right), (0, 0)),
                 mode="constant", constant_values=values)
    return _to_nd(out)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(arr, size[0], size[1], interp=interp)
    return _to_nd(arr)


def random_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src if isinstance(src, NDArray) else _to_nd(_to_np(src))
    out = src.astype(np.float32) - nd_mod.array(np.asarray(mean,
                                                           np.float32))
    if std is not None:
        out = out / nd_mod.array(np.asarray(std, np.float32))
    return out


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    """Random area+aspect crop (inception-style, reference
    `image.py:550`)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(arr, size, interp)


# ---------------------------------------------------------------------------
# Augmenters (reference `image.py:607-1015`)
# ---------------------------------------------------------------------------

class Augmenter(object):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2, **kwargs):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        src = src if isinstance(src, NDArray) else _to_nd(_to_np(src))
        return src.astype(np.float32) * alpha


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * self._coef).sum()
        gray = (3.0 * (1.0 - alpha) / arr.size) * gray
        return _to_nd(arr * alpha + gray)


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * self._coef).sum(axis=2, keepdims=True) * (1.0 - alpha)
        return _to_nd(arr * alpha + gray)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      np.float32)
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        arr = _to_np(src).astype(np.float32)
        return _to_nd(np.dot(arr, t))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting noise (reference `image.py:918`)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        arr = _to_np(src).astype(np.float32) + rgb
        return _to_nd(arr)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.full((3, 3), 1.0 / 3.0, np.float32)

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = _to_np(src).astype(np.float32)
            return _to_nd(np.dot(arr, self.mat))
        return src if isinstance(src, NDArray) else _to_nd(_to_np(src))


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return _to_nd(_to_np(src)[:, ::-1].copy())
        return src if isinstance(src, NDArray) else _to_nd(_to_np(src))


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        src = src if isinstance(src, NDArray) else _to_nd(_to_np(src))
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter pipeline factory (reference `image.py:1017`)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and len(np.atleast_1d(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Python-side image iterator over recordio or an image list
    (reference `image.py:1131`)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32", **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3 or data_shape[0] not in (1, 3):
            raise MXNetError("data_shape must be (C,H,W)")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.dtype = dtype
        self.data_name = data_name
        self.label_name = label_name

        self.imgrec = None
        self.seq: Optional[List] = None
        self.imglist = {}
        if path_imgrec:
            idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + \
                ".idx"
            if os.path.exists(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(idx_path,
                                                         path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist or imglist is not None:
            if path_imglist:
                with open(path_imglist) as fin:
                    imglist = []
                    for line in fin:
                        parts = line.strip().split("\t")
                        imglist.append([float(parts[0])] +
                                       [float(x) for x in parts[1:-1]] +
                                       [parts[-1]])
                        imglist[-1][0] = int(imglist[-1][0])
            self.seq = []
            for entry in imglist:
                key = int(entry[0]) if len(entry) > 2 or isinstance(
                    entry[0], (int, float)) else entry[0]
                label = np.asarray(entry[1:-1] if len(entry) > 2
                                   else entry[1:2], np.float32)
                self.imglist[key] = (label, entry[-1])
                self.seq.append(key)
        else:
            raise MXNetError("need path_imgrec, path_imglist or imglist")
        self.path_root = path_root
        if num_parts > 1 and self.seq is not None:
            self.seq = self.seq[part_index::num_parts]
        self.shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_resize",
                                                    "rand_mirror", "mean",
                                                    "std", "brightness",
                                                    "contrast", "saturation",
                                                    "hue", "pca_noise",
                                                    "rand_gray",
                                                    "inter_method")})
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) +
                         self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def next(self) -> DataBatch:
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), self.dtype)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               np.float32)
        i = 0
        while i < self.batch_size:
            try:
                label, s = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                break
            img = imdecode(s, flag=1 if c == 3 else 0)
            for aug in self.auglist:
                img = aug(img)
            arr = _to_np(img).astype(self.dtype)
            if arr.shape[:2] != (h, w):
                arr = _to_np(imresize(arr, w, h))
            batch_data[i] = arr.transpose(2, 0, 1)
            batch_label[i] = np.atleast_1d(np.asarray(label,
                                                      np.float32))[
                :self.label_width]
            i += 1
        pad = self.batch_size - i
        label_out = batch_label[:, 0] if self.label_width == 1 \
            else batch_label
        return DataBatch(data=[nd_mod.array(batch_data)],
                         label=[nd_mod.array(label_out)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
