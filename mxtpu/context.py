"""Device contexts.

Re-design of the reference's `include/mxnet/base.h` ``Context`` and
`python/mxnet/context.py`.  A ``Context`` names a logical device
(``cpu(0)``, ``tpu(0)``...) and maps onto a concrete ``jax.Device``.
The reference's ``gpu(i)`` is accepted as an alias for ``tpu(i)`` so model
scripts written against the reference run with only a context swap (the
north-star requirement in BASELINE.json).

Unlike the reference there is no stream/device-ordinal plumbing below this:
placement is carried by committed jax Arrays, and XLA/PJRT owns streams.
``cpu_pinned``/``cpu_shared`` collapse onto the host CPU device (PJRT host
buffers are already DMA-visible).
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError, getenv

__all__ = [
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "cpu_pinned",
    "cpu_shared",
    "current_context",
    "num_tpus",
    "num_gpus",
    "device_of",
]


class Context(object):
    """A logical device. Usable as a ``with`` scope, like the reference
    (`python/mxnet/context.py:93`)."""

    # type codes kept for API parity with the reference's Context enum
    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = int(device_id)
        self._old_ctx: Optional["Context"] = None

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        if not hasattr(self._default_ctx, "value"):
            self._default_ctx.value = Context("cpu", 0)
        self._old_ctx = self._default_ctx.value
        self._default_ctx.value = self
        return self

    def __exit__(self, *args):
        self._default_ctx.value = self._old_ctx

    # ---- jax mapping -----------------------------------------------------
    @property
    def jax_device(self):
        """The concrete jax.Device backing this context."""
        import jax

        if self.device_typeid == 2:
            devs = _accelerator_devices()
            if not devs:
                raise MXNetError(
                    "no TPU/accelerator devices visible to JAX; "
                    "use mxtpu.cpu() or set JAX_PLATFORMS"
                )
            if self.device_id >= len(devs):
                raise MXNetError(
                    "tpu(%d) requested but only %d device(s) present"
                    % (self.device_id, len(devs))
                )
            return devs[self.device_id]
        devs = _cpu_devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def empty_cache(self):  # parity no-op: PJRT owns the HBM pool
        pass


def _accelerator_devices():
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if devs:
        return devs
    # CPU-only environment (tests force JAX_PLATFORMS=cpu): treat the virtual
    # CPU devices as "chips" so multi-device codepaths still run.
    return jax.devices()


def _cpu_devices():
    import jax

    try:
        return jax.devices("cpu")
    except RuntimeError:
        return jax.devices()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for :func:`tpu` — reference scripts using ``mx.gpu()`` run
    unchanged on the TPU backend."""
    return Context("tpu", device_id)


def num_tpus() -> int:
    return len(_accelerator_devices())


def num_gpus() -> int:
    """Parity alias (reference `mxnet.context.num_gpus`)."""
    import jax

    if any(d.platform != "cpu" for d in jax.devices()):
        return num_tpus()
    return 0


def default_ctx() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        dev = getenv("MXNET_DEFAULT_CONTEXT")
        if dev:
            name, _, idx = dev.partition(":")
            Context._default_ctx.value = Context(name, int(idx or 0))
        else:
            # TPU if one is attached, else CPU.
            import jax

            has_acc = any(d.platform != "cpu" for d in jax.devices())
            Context._default_ctx.value = Context("tpu" if has_acc else "cpu", 0)
    return Context._default_ctx.value


def current_context() -> Context:
    return default_ctx()


def device_of(array) -> Context:
    """Context of an NDArray."""
    return array.ctx
