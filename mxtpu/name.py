"""Automatic symbol naming — `mx.name.NameManager` / `mx.name.Prefix`
(reference `python/mxnet/name.py`).  NameManager lives with the Symbol
machinery; Prefix specializes it to prepend a fixed prefix to every
auto-generated name (explicit names pass through prefixed too, matching
the reference's use for module namespacing)."""
from .symbol.symbol import NameManager

__all__ = ["NameManager", "Prefix"]


class Prefix(NameManager):
    """`with mx.name.Prefix("enc_"):` — every symbol created in the
    scope gets the prefix (reference `name.py:93`)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
