"""RecordIO file format (reference: `python/mxnet/recordio.py:37-378`,
`src/io/image_recordio.h`).

Binary-compatible with the reference's format: records framed by the magic
`0xced7230a`, a length-word whose upper 3 bits carry the continuation
cflag, 4-byte alignment padding, and an `.idx` sidecar of "key\\toffset"
lines.  `IRHeader`/`pack`/`unpack`/`pack_img`/`unpack_img` match the
reference API (image codecs go through PIL if present, else raw numpy
buffers).
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_CFLAG_BITS = 29
_LEN_MASK = (1 << _CFLAG_BITS) - 1


class MXRecordIO(object):
    """Sequential record reader/writer (reference `recordio.py:37`).

    Backed by the native C++ recordio (src/recordio.cc via ctypes) when
    `make -C src` has been run — like the reference, where record IO is
    always native; falls back to pure python otherwise."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        if flag not in ("r", "w"):
            raise MXNetError("flag must be 'r' or 'w'")
        self.open()

    def open(self):
        from . import _native

        self._lib = _native.get_lib()
        self._nat = None
        self._f = None
        if self._lib is not None:
            create = self._lib.MXTPURecordReaderCreate if self.flag == "r" \
                else self._lib.MXTPURecordWriterCreate
            self._nat = create(self.uri.encode())
            if not self._nat and self.flag == "r":
                raise MXNetError("cannot open %s" % self.uri)
        if self._nat is None:
            self._f = open(self.uri, "rb" if self.flag == "r" else "wb")
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._nat is not None:
                if self.flag == "r":
                    self._lib.MXTPURecordReaderClose(self._nat)
                else:
                    self._lib.MXTPURecordWriterClose(self._nat)
                self._nat = None
            else:
                self._f.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *args):
        self.close()

    def tell(self):
        if self._nat is not None:
            fn = self._lib.MXTPURecordWriterTell if self.flag == "w" \
                else self._lib.MXTPURecordReaderTell
            return int(fn(self._nat))
        return self._f.tell()

    def seek(self, pos):
        if self.flag != "r":
            raise MXNetError("seek is read-only")
        if self._nat is not None:
            if self._lib.MXTPURecordReaderSeek(self._nat, int(pos)) != 0:
                raise MXNetError("seek failed")
        else:
            self._f.seek(pos)

    def write(self, buf: bytes):
        if self.flag != "w":
            raise MXNetError("not opened for writing")
        if self._nat is not None:
            if self._lib.MXTPURecordWriterWrite(self._nat, buf,
                                                len(buf)) != 0:
                raise MXNetError("native record write failed")
            return
        length = len(buf)
        header = struct.pack("<II", _MAGIC, length & _LEN_MASK)
        self._f.write(header)
        self._f.write(buf)
        pad = (4 - (length % 4)) % 4
        if pad:
            self._f.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        if self.flag != "r":
            raise MXNetError("not opened for reading")
        if self._nat is not None:
            out = ctypes.POINTER(ctypes.c_char)()
            length = ctypes.c_uint64()
            rc = self._lib.MXTPURecordReaderRead(
                self._nat, ctypes.byref(out), ctypes.byref(length))
            if rc == 1:
                return None
            if rc != 0:
                raise MXNetError("native record read failed (%d)" % rc)
            buf = ctypes.string_at(out, length.value)
            self._lib.MXTPUBufferFree(out)
            return buf
        header = self._f.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic 0x%x" % magic)
        length = lrec & _LEN_MASK
        buf = self._f.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self._f.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file with .idx sidecar (reference
    `recordio.py:212`)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.flag == "w" and getattr(self, "is_open", False):
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (key, self.idx[key]))
        super().close()

    def seek(self, idx):
        MXRecordIO.seek(self, self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a header + payload (reference `recordio.py:340`)."""
    flag = header.flag
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)) and not np.isscalar(label):
        label = np.asarray(label, dtype=np.float32)
        header_bytes = struct.pack(_IR_FORMAT, len(label), 0.0, header.id,
                                   header.id2)
        return header_bytes + label.tobytes() + s
    return struct.pack(_IR_FORMAT, 0, float(label), header.id, header.id2) + s


def unpack(s: bytes):
    """Unpack to (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = np.frombuffer(s[:flag * 4], dtype=np.float32)
        return IRHeader(flag, arr, id_, id2), s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header: IRHeader, img: np.ndarray, quality=95,
             img_fmt=".jpg") -> bytes:
    """Pack an image; uses PIL when available else raw .npy bytes."""
    try:
        import io

        from PIL import Image

        buf = io.BytesIO()
        mode = "L" if img.ndim == 2 else "RGB"
        Image.fromarray(img.astype(np.uint8), mode=mode).save(
            buf, format="JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG",
            quality=quality)
        return pack(header, buf.getvalue())
    except ImportError:
        import io

        buf = io.BytesIO()
        np.save(buf, img)
        return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor=-1):
    header, payload = unpack(s)
    try:
        import io

        from PIL import Image

        img = np.asarray(Image.open(io.BytesIO(payload)))
    except Exception:
        import io

        img = np.load(io.BytesIO(payload), allow_pickle=False)
    return header, img
