"""Evaluation metrics (reference: `python/mxnet/metric.py`).

Same registry + classes: Accuracy, TopKAccuracy, F1, MCC, Perplexity,
MAE/MSE/RMSE, CrossEntropy, NegativeLogLikelihood, PearsonCorrelation,
Loss, CompositeEvalMetric, CustomMetric/np().
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as _np

from .base import MXNetError

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create"]

_METRIC_REGISTRY: Dict[str, type] = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def _alias(name, klass):
    _METRIC_REGISTRY[name] = klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    key = str(metric).lower()
    if key not in _METRIC_REGISTRY:
        raise MXNetError("unknown metric %r" % metric)
    return _METRIC_REGISTRY[key](*args, **kwargs)


def _asnumpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


class EvalMetric(object):
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label: Dict[str, Any], pred: Dict[str, Any]):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_config(self):
        config = {"metric": self.__class__.__name__, "name": self.name}
        config.update(self._kwargs)
        return config

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()
        super().reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
                value = [value]
            names.extend(name)
            values.extend(value)
        return names, values


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label).astype(_np.int32)
            pred = _asnumpy(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int32)
            self.sum_metric += (pred.flat == label.flat).sum()
            self.num_inst += len(label.flat)


acc = _alias("acc", Accuracy)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__("%s_%d" % (name, top_k), output_names, label_names,
                         top_k=top_k)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label).astype(_np.int32)
            pred = _asnumpy(pred)
            topk = _np.argpartition(pred, -self.top_k,
                                   axis=-1)[..., -self.top_k:]
            for k in range(self.top_k):
                self.sum_metric += (topk[..., k].flat == label.flat).sum()
            self.num_inst += len(label.flat)


_alias("top_k_acc", TopKAccuracy)
_alias("top_k_accuracy", TopKAccuracy)


class _BinaryClassificationMetrics(object):
    def __init__(self):
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, label, pred):
        pred_label = pred.argmax(axis=1) if pred.ndim > 1 else (pred > 0.5)
        label = label.astype(_np.int32).reshape(-1)
        pred_label = pred_label.astype(_np.int32).reshape(-1)
        self.tp += int(((pred_label == 1) & (label == 1)).sum())
        self.fp += int(((pred_label == 1) & (label == 0)).sum())
        self.tn += int(((pred_label == 0) & (label == 0)).sum())
        self.fn += int(((pred_label == 0) & (label == 1)).sum())

    @property
    def precision(self):
        return self.tp / max(self.tp + self.fp, 1)

    @property
    def recall(self):
        return self.tp / max(self.tp + self.fn, 1)

    @property
    def fscore(self):
        p, r = self.precision, self.recall
        return 2 * p * r / max(p + r, 1e-12)

    @property
    def matthewscc(self):
        num = self.tp * self.tn - self.fp * self.fn
        den = math.sqrt(max((self.tp + self.fp) * (self.tp + self.fn) *
                            (self.tn + self.fp) * (self.tn + self.fn), 1))
        return num / den

    @property
    def total_examples(self):
        return self.tp + self.fp + self.tn + self.fn

    def reset_stats(self):
        self.tp = self.fp = self.tn = self.fn = 0


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self.metrics.update(_asnumpy(label), _asnumpy(pred))
        if self.average == "micro":
            self.sum_metric = self.metrics.fscore * \
                self.metrics.total_examples
            self.num_inst = self.metrics.total_examples

    def get(self):
        if self.average == "micro":
            return super().get()
        return (self.name, self.metrics.fscore)

    def reset(self):
        super().reset()
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self.metrics.update(_asnumpy(label), _asnumpy(pred))

    def get(self):
        return (self.name, self.metrics.matthewscc)

    def reset(self):
        super().reset()
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _asnumpy(label).astype(_np.int64).reshape(-1)
            pred = _asnumpy(pred).reshape(len(label), -1)
            probs = pred[_np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= _np.sum(_np.log(_np.maximum(1e-10, probs)))
            num += len(label)
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            if label.ndim == 1 and pred.ndim == 2:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += _np.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            if label.ndim == 1 and pred.ndim == 2:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            if label.ndim == 1 and pred.ndim == 2:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += math.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label).ravel().astype(_np.int64)
            pred = _asnumpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


_alias("ce", CrossEntropy)


@register
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    update = CrossEntropy.update


_alias("nll_loss", NegativeLogLikelihood)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label).ravel()
            pred = _asnumpy(pred).ravel()
            self.sum_metric += _np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of the raw loss outputs (reference Loss metric)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = _asnumpy(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _asnumpy(label)
            pred = _asnumpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
