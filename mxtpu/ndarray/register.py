"""Attach every registered op as a function on the `mxtpu.nd` namespace.

This is the analog of the reference's import-time op codegen:
`_init_op_module` (`python/mxnet/base.py:578`) enumerates the C-side op
registry and generates Python wrappers (`python/mxnet/ndarray/register.py:
30-169`).  Here the registry is in-process, so "codegen" is closure
creation — same API result: ``nd.elemwise_add(a, b)``, ``nd.FullyConnected
(x, w, b, num_hidden=...)``, with ``out=`` support.
"""
from __future__ import annotations

import sys
from typing import Any

from ..ops import registry as _reg
from .ndarray import NDArray, imperative_invoke


def _make_ndarray_function(name: str, opdef):
    def fn(*args, out=None, name=None, **kwargs):  # noqa: A002 - parity
        nd_args = [a for a in args]
        n_out = opdef.n_outputs(kwargs)
        res = imperative_invoke(opdef.name, *nd_args, out=out, **kwargs)
        if len(res) == 1:
            return res[0]
        return list(res)

    fn.__name__ = name
    fn.__doc__ = opdef.doc or ("%s (auto-generated TPU-native op wrapper)" % name)
    fn.__module__ = "mxtpu.ndarray"
    return fn


def _init_op_module(target_module):
    registry = _reg._OP_REGISTRY
    seen = set()
    for name, opdef in registry.items():
        if name in seen:
            continue
        seen.add(name)
        public_name = name
        setattr(target_module, public_name, _make_ndarray_function(public_name,
                                                                  opdef))
    # ops registered after this module initialized (late imports, user
    # registrations) still get nd.* functions
    _reg.add_post_register_hook(
        lambda n, od: setattr(target_module, n,
                              _make_ndarray_function(n, od)))
