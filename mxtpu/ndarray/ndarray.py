"""NDArray: the imperative tensor.

TPU-native re-design of the reference's NDArray
(`include/mxnet/ndarray.h:82`, `python/mxnet/ndarray/ndarray.py:177`) and
of the imperative invoke path (`src/imperative/imperative.cc:38-119`,
`python/mxnet/_ctypes/ndarray.py:65-83`).

Design notes (vs the reference):
  * The reference NDArray owns a Storage handle + an engine variable; reads
    block via WaitToRead.  Here the payload is a committed `jax.Array`:
    PJRT is already an async, stream-ordered runtime, so the dependency
    engine's ordering job for pure compute is done by the runtime itself.
    `wait_to_read` maps to `block_until_ready`; `asnumpy` device-transfers.
  * Every operator call funnels through :func:`imperative_invoke` — the
    analog of `MXImperativeInvokeEx -> Imperative::Invoke` — which hits a
    per-(op, attrs) jitted executable (XLA recompiles per shape/dtype
    signature and caches, the reference's executable-cache discipline).
  * In-place mutation (`a[:] = x`, `+=`, optimizer updates) rebinds the
    wrapper's payload and bumps a version counter (the reference's
    engine-var version, `include/mxnet/engine.h:44-61`).
"""
from __future__ import annotations

import functools
import operator
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..base import MXNetError, _Null, np_dtype, shape2tuple
from ..context import Context, current_context
from ..ops import registry as _reg
from .. import autograd as _ag

__all__ = [
    "NDArray",
    "imperative_invoke",
    "array",
    "zeros",
    "ones",
    "full",
    "empty",
    "arange",
    "eye",
    "concat",
    "stack",
    "split",
    "moveaxis",
    "waitall",
    "save",
    "load",
    "from_numpy",
    "from_jax",
    "maximum",
    "minimum",
    "from_dlpack",
    "to_dlpack_for_read",
    "to_dlpack_for_write",
]


def _dev_of_ctx(ctx: Context):
    return ctx.jax_device


class NDArray(object):
    """A fixed-size multi-dimensional array on a device."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_marked", "_entry",
                 "_version", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, _committed: bool = False):
        import jax

        if ctx is None:
            ctx = current_context()
        if not _committed:
            data = jax.device_put(data, _dev_of_ctx(ctx))
        self._data = data
        self._ctx = ctx
        self._grad: Optional["NDArray"] = None
        self._grad_req = "write"
        self._marked = False
        self._entry = None  # (TapeNode, out_index) when produced under record
        self._version = 0

    # -- payload management -------------------------------------------------
    def _set_jax(self, data, bump: bool = True):
        """Rebind payload (in-place write semantics; bumps version like the
        reference's engine-var version on write)."""
        self._data = data
        if bump:
            self._version += 1
            self._entry = None  # an in-place write invalidates the tape link

    @property
    def dlpack(self):
        return self._data.__dlpack__()

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def ctx(self) -> Context:
        return self._ctx

    context = ctx

    @property
    def stype(self) -> str:
        return "default"

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def T(self) -> "NDArray":
        if self.ndim < 2:
            return self
        return imperative_invoke("transpose", self)[0]

    # -- sync / host transfer ----------------------------------------------
    def wait_to_read(self):
        """Block until the value is computed (reference:
        `python/mxnet/ndarray/ndarray.py:1795`; async errors surface here
        like `threaded_engine.h:362-372`)."""
        try:
            self._data.block_until_ready()
        except Exception as e:  # deferred XLA error surfaces here
            raise MXNetError(str(e)) from e
        return self

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self.wait_to_read()._data)

    def to_dlpack_for_read(self):
        """Zero-copy DLPack capsule over the device buffer (reference
        `MXNDArrayToDLPackForRead`, `include/mxnet/c_api.h`).  Works
        with any DLPack consumer, e.g.
        ``torch.utils.dlpack.from_dlpack``."""
        return self.wait_to_read()._data.__dlpack__()

    def to_dlpack_for_write(self):
        """Reference `MXNDArrayToDLPackForWrite`.  jax.Array buffers
        are immutable, so writable export cannot be honored — the
        reference's in-place-mutation contract would corrupt the XLA
        buffer cache.  Raises with the supported alternative."""
        raise MXNetError(
            "to_dlpack_for_write is not supported: XLA device buffers "
            "are immutable. Export with to_dlpack_for_read, mutate in "
            "the consumer framework, and re-import with nd.from_dlpack")

    def __dlpack__(self, **kwargs):
        return self.wait_to_read()._data.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self.wait_to_read()._data.__dlpack_device__()

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("The truth value of an NDArray with multiple elements "
                         "is ambiguous")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        try:
            arr = self.asnumpy()
            body = str(arr)
        except Exception as e:  # pragma: no cover
            body = "<unrealized: %s>" % e
        return "%s\n<NDArray %s @%s>" % (body, "x".join(map(str, self.shape)), self._ctx)

    # -- conversion / movement ----------------------------------------------
    def astype(self, dtype, copy: bool = True) -> "NDArray":
        dt = np_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        return imperative_invoke("Cast", self, dtype=dt.name)[0]

    def copy(self) -> "NDArray":
        return imperative_invoke("_copy", self)[0]

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        import jax

        if isinstance(other, Context):
            out = NDArray(jax.device_put(self._data, _dev_of_ctx(other)),
                          ctx=other, _committed=True)
            return out
        if not isinstance(other, NDArray):
            raise TypeError("copyto target must be NDArray or Context")
        if other.stype != "default":
            from .sparse import cast_storage
            return cast_storage(self, other.stype).copyto(other)
        data = jax.device_put(self._data, _dev_of_ctx(other.ctx))
        if data.dtype != other._data.dtype:
            data = data.astype(other._data.dtype)
        if tuple(data.shape) != other.shape:
            raise MXNetError("copyto shape mismatch %s vs %s" % (self.shape, other.shape))
        other._set_jax(data)
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def as_in_ctx(self, ctx: Context) -> "NDArray":
        return self.as_in_context(ctx)

    def detach(self) -> "NDArray":
        out = NDArray(self._data, ctx=self._ctx, _committed=True)
        return out

    def tostype(self, stype: str) -> "NDArray":
        if stype != "default":
            from .sparse import cast_storage
            return cast_storage(self, stype)
        return self

    # -- autograd -----------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype: Optional[str] = None):
        """Attach a gradient buffer (reference:
        `python/mxnet/ndarray/ndarray.py` attach_grad → MXAutogradMarkVariables).
        ``stype='row_sparse'`` makes the buffer a RowSparseNDArray so
        embedding-style gradients stay sparse end to end."""
        import jax.numpy as jnp

        if stype == "row_sparse":
            from . import sparse as _sp

            grad = _sp.zeros("row_sparse", self.shape, ctx=self._ctx,
                             dtype=self._data.dtype)
        else:
            grad = NDArray(jnp.zeros(self.shape, dtype=self._data.dtype),
                           ctx=self._ctx)
        self._grad = grad
        self._grad_req = grad_req
        self._marked = grad_req != "null"
        self._entry = None

    def backward(self, out_grad: Optional["NDArray"] = None, retain_graph: bool = False,
                 train_mode: bool = True):
        _ag.backward([self], [out_grad], retain_graph=retain_graph,
                     train_mode=train_mode)

    # -- indexing -----------------------------------------------------------
    def _canon_index(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(k._data if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        key = self._canon_index(key)
        if isinstance(key, slice) and key.start is None and key.stop is None and key.step is None:
            return self
        # under record, indexing must tape (reference: slicing emits a
        # `slice`/`gather_nd` NNVM node) — otherwise downstream grads
        # silently vanish at the first subscript
        if _ag.is_recording() and (self._entry is not None or
                                   self._marked):
            outs, node = _ag._record_fn(
                "getitem", lambda d: (d[key],), [self], [self._data])
            out = NDArray(outs[0], ctx=self._ctx, _committed=True)
            if node is not None:
                out._entry = (node, 0)
            return out
        data = self._data[key]
        out = NDArray(data, ctx=self._ctx, _committed=True)
        return out

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        key = self._canon_index(key)
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, slice) and key.start is None and key.stop is None and key.step is None:
            if hasattr(value, "shape") and tuple(np.broadcast_shapes(tuple(value.shape), self.shape)) != self.shape:
                raise MXNetError("shape mismatch in assignment")
            newdata = jnp.broadcast_to(jnp.asarray(value, dtype=self._data.dtype), self.shape)
        else:
            newdata = self._data.at[key].set(jnp.asarray(value, dtype=self._data.dtype))
        self._set_jax(newdata)

    # -- shape manipulation convenience (routes through registered ops) -----
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if not shape:
            shape = kwargs.get("shape", ())
        return imperative_invoke("Reshape", self, shape=tuple(shape))[0]

    def reshape_like(self, other: "NDArray") -> "NDArray":
        return imperative_invoke("reshape_like", self, other)[0]

    def expand_dims(self, axis: int) -> "NDArray":
        return imperative_invoke("expand_dims", self, axis=axis)[0]

    def squeeze(self, axis=None) -> "NDArray":
        return imperative_invoke("squeeze", self, axis=axis)[0]

    def transpose(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return imperative_invoke("transpose", self, axes=axes if axes else None)[0]

    def flatten(self) -> "NDArray":
        return imperative_invoke("Flatten", self)[0]

    def swapaxes(self, dim1: int, dim2: int) -> "NDArray":
        return imperative_invoke("SwapAxis", self, dim1=dim1, dim2=dim2)[0]

    def flip(self, axis) -> "NDArray":
        return imperative_invoke("reverse", self, axis=axis)[0]

    def broadcast_to(self, shape) -> "NDArray":
        return imperative_invoke("broadcast_to", self, shape=tuple(shape))[0]

    def broadcast_like(self, other: "NDArray") -> "NDArray":
        return imperative_invoke("broadcast_like", self, other)[0]

    def slice(self, begin, end, step=None) -> "NDArray":
        return imperative_invoke("slice", self, begin=tuple(begin), end=tuple(end),
                                 step=tuple(step) if step else None)[0]

    def slice_axis(self, axis: int, begin: int, end: Optional[int]) -> "NDArray":
        return imperative_invoke("slice_axis", self, axis=axis, begin=begin, end=end)[0]

    def take(self, indices: "NDArray", axis: int = 0, mode: str = "clip") -> "NDArray":
        return imperative_invoke("take", self, indices, axis=axis, mode=mode)[0]

    def one_hot(self, depth: int, on_value=1.0, off_value=0.0, dtype="float32") -> "NDArray":
        return imperative_invoke("one_hot", self, depth=depth, on_value=on_value,
                                 off_value=off_value, dtype=dtype)[0]

    def clip(self, a_min, a_max) -> "NDArray":
        return imperative_invoke("clip", self, a_min=a_min, a_max=a_max)[0]

    def abs(self) -> "NDArray":
        return imperative_invoke("abs", self)[0]

    def sign(self) -> "NDArray":
        return imperative_invoke("sign", self)[0]

    def sqrt(self) -> "NDArray":
        return imperative_invoke("sqrt", self)[0]

    def square(self) -> "NDArray":
        return imperative_invoke("square", self)[0]

    def exp(self) -> "NDArray":
        return imperative_invoke("exp", self)[0]

    def log(self) -> "NDArray":
        return imperative_invoke("log", self)[0]

    def relu(self) -> "NDArray":
        return imperative_invoke("relu", self)[0]

    def sigmoid(self) -> "NDArray":
        return imperative_invoke("sigmoid", self)[0]

    def tanh(self) -> "NDArray":
        return imperative_invoke("tanh", self)[0]

    def softmax(self, axis: int = -1) -> "NDArray":
        return imperative_invoke("softmax", self, axis=axis)[0]

    def log_softmax(self, axis: int = -1) -> "NDArray":
        return imperative_invoke("log_softmax", self, axis=axis)[0]

    # -- reductions ----------------------------------------------------------
    def _reduce(self, op: str, axis=None, keepdims=False, **kw) -> "NDArray":
        return imperative_invoke(op, self, axis=axis, keepdims=keepdims, **kw)[0]

    def sum(self, axis=None, keepdims=False) -> "NDArray":
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False) -> "NDArray":
        return self._reduce("mean", axis, keepdims)

    def prod(self, axis=None, keepdims=False) -> "NDArray":
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False) -> "NDArray":
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False) -> "NDArray":
        return self._reduce("min", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False) -> "NDArray":
        return imperative_invoke("norm", self, ord=ord, axis=axis, keepdims=keepdims)[0]

    def argmax(self, axis=None, keepdims=False) -> "NDArray":
        return imperative_invoke("argmax", self, axis=axis, keepdims=keepdims)[0]

    def argmin(self, axis=None, keepdims=False) -> "NDArray":
        return imperative_invoke("argmin", self, axis=axis, keepdims=keepdims)[0]

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False) -> "NDArray":
        return imperative_invoke("topk", self, axis=axis, k=k, ret_typ=ret_typ,
                                 is_ascend=is_ascend)[0]

    def argsort(self, axis=-1, is_ascend=True) -> "NDArray":
        return imperative_invoke("argsort", self, axis=axis, is_ascend=is_ascend)[0]

    def sort(self, axis=-1, is_ascend=True) -> "NDArray":
        return imperative_invoke("sort", self, axis=axis, is_ascend=is_ascend)[0]

    def dot(self, other: "NDArray", **kw) -> "NDArray":
        return imperative_invoke("dot", self, other, **kw)[0]

    def pick(self, index: "NDArray", axis=-1, keepdims=False, mode="clip") -> "NDArray":
        return imperative_invoke("pick", self, index, axis=axis, keepdims=keepdims,
                                 mode=mode)[0]

    def zeros_like(self) -> "NDArray":
        return imperative_invoke("zeros_like", self)[0]

    def ones_like(self) -> "NDArray":
        return imperative_invoke("ones_like", self)[0]

    # -- arithmetic ----------------------------------------------------------
    _BROADCAST_NAME = {
        "elemwise_add": "broadcast_add", "elemwise_sub": "broadcast_sub",
        "elemwise_mul": "broadcast_mul", "elemwise_div": "broadcast_div",
        "_grad_add": "broadcast_add", "_mod": "broadcast_mod",
        "_power": "broadcast_power", "_maximum": "broadcast_maximum",
        "_minimum": "broadcast_minimum", "_hypot": "broadcast_hypot",
        "_equal": "broadcast_equal", "_not_equal": "broadcast_not_equal",
        "_greater": "broadcast_greater",
        "_greater_equal": "broadcast_greater_equal",
        "_lesser": "broadcast_lesser", "_lesser_equal": "broadcast_lesser_equal",
    }

    def _binary(self, other, op_ew: str, op_sc: str, reverse_sc: Optional[str] = None,
                swap: bool = False):
        if isinstance(other, NDArray):
            a, b = (other, self) if swap else (self, other)
            if a.shape == b.shape:
                return imperative_invoke(op_ew, a, b)[0]
            return imperative_invoke(self._BROADCAST_NAME[op_ew], a, b)[0]
        if isinstance(other, (int, float, np.generic)):
            name = reverse_sc if (swap and reverse_sc) else op_sc
            return imperative_invoke(name, self, scalar=float(other))[0]
        return NotImplemented

    def __add__(self, other):
        return self._binary(other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar", "_rminus_scalar",
                            swap=True)

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar", "_rdiv_scalar",
                            swap=True)

    def __mod__(self, other):
        return self._binary(other, "_mod", "_mod_scalar")

    def __rmod__(self, other):
        return self._binary(other, "_mod", "_mod_scalar", "_rmod_scalar", swap=True)

    def __pow__(self, other):
        return self._binary(other, "_power", "_power_scalar")

    def __rpow__(self, other):
        return self._binary(other, "_power", "_power_scalar", "_rpower_scalar",
                            swap=True)

    def __matmul__(self, other):
        return imperative_invoke("dot", self, other)[0]

    def __neg__(self):
        return imperative_invoke("negative", self)[0]

    def __abs__(self):
        return imperative_invoke("abs", self)[0]

    def _inplace_result(self, res):
        # keep the tape link when mutating in place under record()
        # (reference: in-place writes bump the var version but stay taped)
        self._set_jax(res._data)
        self._entry = getattr(res, "_entry", None)
        return self

    def __iadd__(self, other):
        return self._inplace_result(self.__add__(other))

    def __isub__(self, other):
        return self._inplace_result(self.__sub__(other))

    def __imul__(self, other):
        return self._inplace_result(self.__mul__(other))

    def __itruediv__(self, other):
        return self._inplace_result(self.__truediv__(other))

    def _compare(self, other, op_ew: str, op_sc: str):
        if isinstance(other, NDArray):
            if other.shape == self.shape:
                return imperative_invoke(op_ew, self, other)[0]
            return imperative_invoke("broadcast" + op_ew, self, other)[0]
        return imperative_invoke(op_sc, self, scalar=float(other))[0]

    def __eq__(self, other):
        if other is None:
            return False
        return self._compare(other, "_equal", "_equal_scalar")

    def __ne__(self, other):
        if other is None:
            return True
        return self._compare(other, "_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return self._compare(other, "_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._compare(other, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._compare(other, "_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._compare(other, "_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__


# ---------------------------------------------------------------------------
# Imperative invoke — the single funnel every op call goes through
# (reference: `Imperative::Invoke`, `src/imperative/imperative.cc:87-119`).
# ---------------------------------------------------------------------------

def imperative_invoke(op_name: str, *inputs, out=None,
                      _full_outputs: bool = False,
                      **attrs) -> Tuple[NDArray, ...]:
    from .. import profiler as _prof

    if _prof.is_recording("imperative"):
        with _prof.span(op_name, "operator"):
            return _imperative_invoke_impl(op_name, *inputs, out=out,
                                           _full_outputs=_full_outputs,
                                           **attrs)
    return _imperative_invoke_impl(op_name, *inputs, out=out,
                                   _full_outputs=_full_outputs, **attrs)


def _imperative_invoke_impl(op_name: str, *inputs, out=None,
                            _full_outputs: bool = False,
                            **attrs) -> Tuple[NDArray, ...]:
    opdef = _reg.get_op(op_name)

    # drop None/_Null attrs so they don't pollute the jit cache key
    attrs = {k: v for k, v in attrs.items() if v is not None and v is not _Null}
    if opdef.train_aware and "is_train" not in attrs:
        attrs["is_train"] = _ag.is_training()

    nd_inputs: List[NDArray] = []
    for x in inputs:
        if isinstance(x, NDArray):
            # storage-fallback dispatch (reference
            # `attach_op_execs_pass.cc:45`): ops without a sparse
            # formulation run on the densified array; sparse-native
            # kernels live in ndarray/sparse.py and bypass this funnel
            if x.stype != "default":
                x = x.todense()
            nd_inputs.append(x)
        elif isinstance(x, (int, float, np.generic, np.ndarray, list, tuple)):
            nd_inputs.append(array(x))
        else:
            nd_inputs.append(x)  # raw jax array (internal use)

    ctx = nd_inputs[0].ctx if nd_inputs and isinstance(nd_inputs[0], NDArray) \
        else attrs.pop("ctx", None) or current_context()
    if "ctx" in attrs:
        ctx = attrs.pop("ctx") or ctx
        if isinstance(ctx, str):
            name, _, idx = ctx.partition("(")
            ctx = Context(name, int(idx.rstrip(")") or 0))

    jax_inputs = [x._data if isinstance(x, NDArray) else x for x in nd_inputs]

    rng_key = None
    if opdef.needs_rng:
        from .. import random as _rnd
        rng_key = _rnd._next_key()

    node = None
    if _ag.is_recording() and opdef.differentiable:
        outs, node = _ag._record_op(opdef, nd_inputs, jax_inputs, attrs, rng_key)
    else:
        outs = _reg.invoke_jax(opdef, jax_inputs, attrs, rng_key)

    # init ops: place on requested ctx
    if not nd_inputs:
        import jax

        dev = _dev_of_ctx(ctx)
        outs = tuple(jax.device_put(o, dev) for o in outs)

    results = []
    for i, o in enumerate(outs):
        nd = NDArray(o, ctx=ctx, _committed=True)
        if node is not None:
            nd._entry = (node, i)
        results.append(nd)

    # hide non-visible outputs (reference NumVisibleOutputs — e.g.
    # BatchNorm's batch mean/var); internal callers pass _full_outputs
    if not _full_outputs:
        n_vis = opdef.n_visible_outputs(attrs)
        if n_vis < len(results):
            results = results[:n_vis]

    if out is not None:
        outs_list = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs_list, results):
            if dst.stype != "default":
                raise MXNetError(
                    "out= with %s storage is not supported for %s"
                    % (dst.stype, op_name))
            dst._set_jax(src._data)
        return tuple(outs_list)
    return tuple(results)


# ---------------------------------------------------------------------------
# Creation / utility functions (reference: `python/mxnet/ndarray/ndarray.py`
# zeros/ones/full/array/arange + `ndarray/utils.py` save/load)
# ---------------------------------------------------------------------------

def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source_array, NDArray):
        res = source_array.copy() if ctx is None or ctx == source_array.ctx \
            else source_array.as_in_context(ctx)
        if dtype is not None and res.dtype != np_dtype(dtype):
            res = res.astype(dtype)
        return res
    # reference rule (`python/mxnet/ndarray/ndarray.py` array()): numpy
    # sources keep their dtype; python lists/scalars default to float32
    if dtype is None:
        dtype = source_array.dtype if isinstance(source_array, np.ndarray) \
            else np.float32
        if np.dtype(dtype) == np.float64:
            dtype = np.float32  # TPU-native default: fp64 is emulated on TPU
    arr = np.asarray(source_array).astype(np_dtype(dtype), copy=False)
    return NDArray(arr, ctx=ctx)


def from_numpy(a: np.ndarray, ctx=None) -> NDArray:
    return array(a, ctx=ctx)


def from_jax(a, ctx: Optional[Context] = None) -> NDArray:
    return NDArray(a, ctx=ctx or current_context(), _committed=True)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    return imperative_invoke("_zeros", shape=shape2tuple(shape),
                             dtype=np_dtype(dtype).name, ctx=ctx)[0]


def ones(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    return imperative_invoke("_ones", shape=shape2tuple(shape),
                             dtype=np_dtype(dtype).name, ctx=ctx)[0]


def full(shape, val, ctx=None, dtype=None, **kwargs) -> NDArray:
    return imperative_invoke("_full", shape=shape2tuple(shape), value=float(val),
                             dtype=np_dtype(dtype).name, ctx=ctx)[0]


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    return imperative_invoke("_arange", start=float(start),
                             stop=float(stop) if stop is not None else None,
                             step=float(step), repeat=int(repeat),
                             dtype=np_dtype(dtype).name, ctx=ctx)[0]


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    return imperative_invoke("_eye", N=int(N), M=int(M), k=int(k),
                             dtype=np_dtype(dtype).name, ctx=ctx)[0]


def concat(*arrays, dim: int = 1) -> NDArray:
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return imperative_invoke("Concat", *arrays, dim=dim)[0]


def stack(*arrays, axis: int = 0) -> NDArray:
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return imperative_invoke("stack", *arrays, axis=axis)[0]


def split(data, num_outputs, axis=1, squeeze_axis=False):
    outs = imperative_invoke("SliceChannel", data, num_outputs=num_outputs,
                             axis=axis, squeeze_axis=squeeze_axis)
    return list(outs) if len(outs) > 1 else outs[0]


def moveaxis(data, source, destination) -> NDArray:
    return imperative_invoke("moveaxis", data, source=source,
                             destination=destination)[0]


def waitall():
    """Block until all async work completes (reference:
    `python/mxnet/ndarray/ndarray.py:156` → Engine WaitForAll).

    Blocks on every live array.  A sentinel-program shortcut ("enqueue
    a trivial program last, wait for it") is NOT sound here: PJRT only
    orders programs that have data dependencies, so an independent
    sentinel can complete while earlier-enqueued work is still running
    (measured on the remote-tunnel TPU client: a sentinel returned
    ~2.3s before a chained matmul stream finished).  `is_ready()` is a
    client-local check, so already-finished arrays cost no RPC."""
    import jax

    try:
        jax.effects_barrier()
        pending = []
        for arr in jax.live_arrays():
            try:
                if not arr.is_ready():
                    pending.append(arr)
            except Exception:
                pending.append(arr)
        if pending:
            jax.block_until_ready(pending)
    except Exception as e:
        raise MXNetError(str(e)) from e


# -- serialization (reference: NDArray::Save/Load `src/ndarray/ndarray.cc`,
#    python `ndarray/utils.py:149-222`; format here is npz, not the
#    reference binary layout — same API, container swapped) ----------------

def save(fname, data):
    """`fname` may be a path or a writable binary file object (the C
    ABI's MXNDArraySaveRawBytes serializes through a BytesIO)."""
    if isinstance(data, NDArray):
        payload = {"0": data.asnumpy()}
        keys = None
    elif isinstance(data, (list, tuple)):
        payload = {str(i): d.asnumpy() for i, d in enumerate(data)}
        keys = None
    elif isinstance(data, dict):
        payload = {k: v.asnumpy() for k, v in data.items()}
        keys = list(data.keys())
    else:
        raise TypeError("unsupported data for save: %r" % type(data))
    kw = dict(__keys__=np.array(keys if keys is not None else [],
                                dtype=object), **payload)
    if hasattr(fname, "write"):
        np.savez(fname, **kw)
    else:
        # temp+fsync+rename: a crash mid-save never truncates an
        # existing params file (mxtpu/resilience.py)
        from ..resilience import atomic_write

        with atomic_write(fname) as f:
            np.savez(f, **kw)


def load(fname):
    """`fname` may be a path or a readable binary file object."""
    with np.load(fname, allow_pickle=True) as zf:
        keys = list(zf["__keys__"]) if "__keys__" in zf else []
        names = [k for k in zf.files if k != "__keys__"]
        if keys:
            return {str(k): array(zf[str(k)]) for k in keys}
        try:
            names_sorted = sorted(names, key=int)
            return [array(zf[n]) for n in names_sorted]
        except ValueError:
            return {n: array(zf[n]) for n in names}


def from_dlpack(ext_tensor) -> NDArray:
    """Construct an NDArray from any DLPack producer — a capsule from
    `to_dlpack_for_read`, or an object with `__dlpack__` (torch/numpy/
    cupy tensors).  Zero-copy when the producer lives on a compatible
    device (reference `MXNDArrayFromDLPack`)."""
    import jax.numpy as jnp

    return NDArray(jnp.from_dlpack(ext_tensor), _committed=True)


def to_dlpack_for_read(data: NDArray):
    """Module-level mirror of `NDArray.to_dlpack_for_read` (reference
    `mx.nd.to_dlpack_for_read`)."""
    return data.to_dlpack_for_read()


def to_dlpack_for_write(data: NDArray):
    """Module-level mirror of `NDArray.to_dlpack_for_write` — always
    raises; see the method docstring."""
    return data.to_dlpack_for_write()


def _commutative_binary(name, op_ew, op_sc, host_fn, host_ew):
    def fn(lhs, rhs):
        if not isinstance(lhs, NDArray) and not isinstance(rhs, NDArray):
            # elementwise for array-likes; Python max/min only handles
            # scalars (multi-element arrays raise ambiguous-truth-value)
            if isinstance(lhs, (int, float, np.generic)) and \
                    isinstance(rhs, (int, float, np.generic)):
                return host_fn(lhs, rhs)
            return host_ew(lhs, rhs)
        if isinstance(rhs, NDArray) and not isinstance(lhs, NDArray):
            lhs, rhs = rhs, lhs  # commutative: swap is free
        if not isinstance(rhs, (NDArray, int, float, np.generic)):
            try:
                rhs = array(rhs)  # lists/np arrays coerce (f32 default)
            except Exception:
                raise TypeError("%s: unsupported operand type %r"
                                % (name, type(rhs))) from None
        return lhs._binary(rhs, op_ew, op_sc)

    fn.__name__ = fn.__qualname__ = name
    fn.__doc__ = ("Elementwise %s of arrays or scalars (reference "
                  "`mx.nd.%s`); dispatch incl. broadcasting rides "
                  "NDArray._binary." % (name, name))
    return fn


maximum = _commutative_binary("maximum", "_maximum", "_maximum_scalar",
                              max, np.maximum)
minimum = _commutative_binary("minimum", "_minimum", "_minimum_scalar",
                              min, np.minimum)
