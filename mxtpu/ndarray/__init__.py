"""`mxtpu.nd` — imperative NDArray API (reference: `python/mxnet/ndarray/`).

All registered ops are attached to this module at import (codegen analog);
plus the NDArray class and creation/serialization helpers.
"""
import sys as _sys
import types as _types

from .ndarray import (
    NDArray,
    imperative_invoke,
    array,
    zeros,
    ones,
    full,
    empty,
    arange,
    eye,
    concat,
    stack,
    split,
    moveaxis,
    waitall,
    maximum,
    minimum,
    from_dlpack,
    to_dlpack_for_read,
    to_dlpack_for_write,
    save,
    load,
    from_numpy,
    from_jax,
)
from . import register as _register_mod

_this = _sys.modules[__name__]
_register_mod._init_op_module(_this)

# creation helpers shadow same-named generated wrappers on purpose
_this.array = array
_this.zeros = zeros
_this.ones = ones
_this.full = full
_this.empty = empty
_this.arange = arange
_this.eye = eye
_this.concat = concat
_this.stack = stack
_this.split = split
_this.save = save
_this.load = load

# `nd.random` sub-namespace (reference: mxnet.ndarray.random)
from .. import random as random  # noqa: E402

# `nd.sparse` sub-namespace (reference: mxnet.ndarray.sparse)
from . import sparse  # noqa: E402
from .sparse import CSRNDArray, RowSparseNDArray  # noqa: E402

# `nd.contrib` sub-namespace: expose _contrib_* ops without the prefix
contrib = _types.ModuleType(__name__ + ".contrib")
for _name in dir(_this):
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], getattr(_this, _name))
_sys.modules[contrib.__name__] = contrib

# `nd.linalg` sub-namespace
linalg = _types.ModuleType(__name__ + ".linalg")
for _name in dir(_this):
    if _name.startswith("_linalg_"):
        setattr(linalg, _name[len("_linalg_"):], getattr(_this, _name))
_sys.modules[linalg.__name__] = linalg

# `nd.image` sub-namespace
image = _types.ModuleType(__name__ + ".image")
for _name in dir(_this):
    if _name.startswith("_image_"):
        setattr(image, _name[len("_image_"):], getattr(_this, _name))
_sys.modules[image.__name__] = image


def _alias_late_op(_name, _opdef):
    # keep the prefix-stripped sub-namespaces in sync with ops
    # registered after this package imported
    for prefix, ns in (("_contrib_", contrib), ("_linalg_", linalg),
                       ("_image_", image)):
        if _name.startswith(prefix):
            setattr(ns, _name[len(prefix):], getattr(_this, _name))


from ..ops import registry as _late_reg  # noqa: E402

_late_reg.add_post_register_hook(_alias_late_op)
