"""Sparse NDArrays: CSR and row-sparse.

TPU-native re-design of the reference sparse stack
(`include/mxnet/ndarray.h:61-66` NDArrayStorageType, dense/row_sparse/csr
chunks with aux arrays; python `python/mxnet/ndarray/sparse.py`
CSRNDArray/RowSparseNDArray; kernels under `src/operator/tensor/
cast_storage-inl.h`, `dot-inl.h`, `sparse_retain-inl.h`,
`square_sum-inl.h`).

TPU has no native sparse representation (SURVEY.md §7 "Sparse on TPU"),
so the aux arrays are ordinary dense `jax.Array`s and every kernel is a
gather/scatter/segment-sum formulation that XLA compiles well:

  * row_sparse: ``data`` [nnz_rows, ...] + ``indices`` [nnz_rows]
  * csr:        ``data`` [nnz] + ``indices`` [nnz] + ``indptr`` [m+1]
  * ``cast_storage`` dense<->sparse via nonzero/scatter;
  * ``dot(csr, dense)`` = row-segment-sum of gathered rhs rows scaled by
    values (one fused XLA executable);
  * ops with no sparse formulation fall back to dense, mirroring the
    reference's storage-fallback dispatch
    (`src/executor/attach_op_execs_pass.cc:45`).

Like the reference, a sparse array's unspecified entries are zeros, and
`retain` / `row_sparse_pull` keep only requested rows.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

from ..base import MXNetError, np_dtype
from ..context import Context, current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "cast_storage", "zeros",
           "empty", "array", "dot", "retain", "retain_rows_into",
           "set_rows_into", "add", "elemwise_add"]


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# Classes
# ---------------------------------------------------------------------------

class BaseSparseNDArray(NDArray):
    """Common base (reference `python/mxnet/ndarray/sparse.py:
    BaseSparseNDArray`).  `_data` holds the *packed value* array; the
    logical dense shape lives in `_shape`."""

    __slots__ = ("_shape", "_aux")

    def __init__(self, data, aux, shape, ctx: Optional[Context] = None):
        super().__init__(data, ctx=ctx)
        self._aux = tuple(NDArray(a, ctx=self._ctx)
                          if not isinstance(a, NDArray) else a for a in aux)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def data(self) -> NDArray:
        return NDArray(self._data, ctx=self._ctx, _committed=True)

    @property
    def indices(self) -> NDArray:
        raise NotImplementedError

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(map(str, self._shape)), self._ctx)

    def asnumpy(self) -> np.ndarray:
        return self.todense().asnumpy()

    def astype(self, dtype, copy: bool = True):
        raise MXNetError("astype on sparse: tostype('default') first")

    def todense(self) -> NDArray:
        return cast_storage(self, "default")

    def tostype(self, stype: str):
        return cast_storage(self, stype)

    def copy(self):
        # jax buffers are immutable, so sharing them is safe — a fresh
        # wrapper is a true copy (later _set_jax only rebinds the wrapper)
        return type(self)(self._data, self._aux, self._shape, ctx=self._ctx)

    def copyto(self, other):
        if isinstance(other, Context):
            raise MXNetError("sparse copyto(Context) unsupported; tostype")
        if isinstance(other, BaseSparseNDArray):
            src = self if self.stype == other.stype \
                else cast_storage(self, other.stype)
            other._set_jax(src._data)
            other._aux = src._aux
            other._shape = src._shape
            return other
        return self.todense().copyto(other)

    def __getitem__(self, key):
        raise MXNetError("indexing not supported on %s" % self.stype)

    def __setitem__(self, key, value):
        raise MXNetError("assignment not supported on %s" % self.stype)


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed sparse row array (reference `sparse.py:CSRNDArray`,
    chunk layout `include/mxnet/ndarray.h` kCSRStorage)."""

    @property
    def stype(self) -> str:
        return "csr"

    @property
    def indices(self) -> NDArray:
        return self._aux[1]

    @property
    def indptr(self) -> NDArray:
        return self._aux[0]

    @property
    def nnz(self) -> int:
        return int(self._data.shape[0])

    def dot(self, other, transpose_a=False, **kw):
        return dot(self, other, transpose_a=transpose_a)


class RowSparseNDArray(BaseSparseNDArray):
    """First-dim-sparse array (reference `sparse.py:RowSparseNDArray`,
    kRowSparseStorage): ``data`` holds the stored rows, ``indices`` their
    row ids (sorted, unique)."""

    @property
    def stype(self) -> str:
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return self._aux[0]

    def retain(self, row_ids) -> "RowSparseNDArray":
        return retain(self, row_ids)


# ---------------------------------------------------------------------------
# Constructors (reference `sparse.py: csr_matrix / row_sparse_array`)
# ---------------------------------------------------------------------------

def _as_jax(x, dtype=None):
    import jax.numpy as jnp

    if isinstance(x, NDArray):
        return x._data if dtype is None else x._data.astype(dtype)
    return jnp.asarray(np.asarray(x), dtype=dtype)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """csr_matrix((data, indices, indptr), shape=(m, n)) or from a dense
    NDArray/numpy/scipy source."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape required for (data, indices, indptr)")
        jd = _as_jax(data, np_dtype(dtype) if dtype else None)
        ji = _as_jax(indices, np.int32)
        jp = _as_jax(indptr, np.int32)
        return CSRNDArray(jd, (jp, ji), shape, ctx=ctx)
    if hasattr(arg1, "tocsr"):  # scipy sparse
        sp = arg1.tocsr()
        return csr_matrix((sp.data, sp.indices, sp.indptr), shape=sp.shape,
                          ctx=ctx, dtype=dtype)
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(
        arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    """row_sparse_array((data, indices), shape=...) or from dense."""
    if isinstance(arg1, tuple) and len(arg1) == 2 and not np.isscalar(arg1[0]):
        data, indices = arg1
        if shape is None:
            raise MXNetError("shape required for (data, indices)")
        jd = _as_jax(data, np_dtype(dtype) if dtype else None)
        ji = _as_jax(indices, np.int32)
        return RowSparseNDArray(jd, (ji,), shape, ctx=ctx)
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(
        arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def zeros(stype: str, shape, ctx=None, dtype=None):
    jnp = _jnp()
    dt = np_dtype(dtype)
    shape = tuple(shape)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + shape[1:], dt),
                                (jnp.zeros((0,), np.int32),), shape, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt),
                          (jnp.zeros((shape[0] + 1,), np.int32),
                           jnp.zeros((0,), np.int32)), shape, ctx=ctx)
    if stype == "default":
        from . import ndarray as _nd

        return _nd.zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError("unknown stype %r" % stype)


empty = zeros


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, BaseSparseNDArray):
        return source_array.copy()
    if hasattr(source_array, "tocsr"):
        return csr_matrix(source_array, ctx=ctx, dtype=dtype)
    raise MXNetError("use csr_matrix/row_sparse_array for dense sources")


# ---------------------------------------------------------------------------
# cast_storage (reference `src/operator/tensor/cast_storage-inl.h`)
# ---------------------------------------------------------------------------

def cast_storage(arr: NDArray, stype: str):
    jnp = _jnp()
    src_stype = arr.stype
    if stype == src_stype:
        return arr
    if src_stype == "default":
        a = arr._data
        if stype == "row_sparse":
            # nonzero rows -> gathered data (host-side nonzero: aux shapes
            # are data-dependent, same as the reference's host-synced nnz)
            host = np.asarray(arr.wait_to_read()._data)
            flat = np.abs(host).reshape(host.shape[0], -1) \
                if host.ndim > 1 else np.abs(host)[:, None]
            rows = np.nonzero(flat.sum(axis=1) > 0)[0].astype(np.int32)
            data = jnp.take(a, jnp.asarray(rows), axis=0)
            return RowSparseNDArray(data, (jnp.asarray(rows),), arr.shape,
                                    ctx=arr.ctx)
        if stype == "csr":
            if arr.ndim != 2:
                raise MXNetError("csr requires 2-D")
            host = np.asarray(arr.wait_to_read()._data)
            r, c = np.nonzero(host)
            data = host[r, c]
            indptr = np.zeros(arr.shape[0] + 1, np.int32)
            np.add.at(indptr, r + 1, 1)
            indptr = np.cumsum(indptr)
            return CSRNDArray(jnp.asarray(data), (jnp.asarray(indptr),
                                                  jnp.asarray(c.astype(np.int32))),
                              arr.shape, ctx=arr.ctx)
        raise MXNetError("unknown stype %r" % stype)
    if stype == "default":
        if src_stype == "row_sparse":
            out = jnp.zeros(arr.shape, arr._data.dtype)
            if arr._data.shape[0]:
                out = out.at[arr._aux[0]._data].set(arr._data)
            return NDArray(out, ctx=arr.ctx, _committed=True)
        if src_stype == "csr":
            m, n = arr.shape
            indptr = np.asarray(arr._aux[0]._data)
            rows = np.repeat(np.arange(m, dtype=np.int32),
                             np.diff(indptr))
            out = jnp.zeros((m, n), arr._data.dtype)
            if arr._data.shape[0]:
                out = out.at[jnp.asarray(rows), arr._aux[1]._data].add(
                    arr._data)
            return NDArray(out, ctx=arr.ctx, _committed=True)
    # sparse -> other sparse: via dense
    return cast_storage(cast_storage(arr, "default"), stype)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------

def dot(lhs, rhs, transpose_a: bool = False, transpose_b: bool = False):
    """Sparse dot (reference `src/operator/tensor/dot-inl.h`):
    csr·dense, csrᵀ·dense; formulated as gather + segment-sum so XLA maps
    it to MXU-friendly batched ops."""
    import jax

    jnp = _jnp()
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray):
        if transpose_b:
            raise MXNetError("dot(csr, dense, transpose_b) unsupported")
        m, n = lhs.shape
        indptr = np.asarray(lhs._aux[0]._data)
        rows = jnp.asarray(np.repeat(np.arange(m, dtype=np.int32),
                                     np.diff(indptr)))
        cols, vals = lhs._aux[1]._data, lhs._data
        d = rhs._data
        vec_rhs = d.ndim == 1
        if vec_rhs:
            d = d[:, None]
        if not transpose_a:
            # out[m, k] = Σ_nnz vals * rhs[cols]  segment-summed by row
            gathered = jnp.take(d, cols, axis=0) * vals[:, None]
            out = jax.ops.segment_sum(gathered, rows, num_segments=m)
        else:
            # out[n, k] = Σ_nnz vals * rhs[rows]  scattered by col
            gathered = jnp.take(d, rows, axis=0) * vals[:, None]
            out = jax.ops.segment_sum(gathered, cols, num_segments=n)
        if vec_rhs:
            out = out[:, 0]
        result = NDArray(out, ctx=rhs.ctx, _committed=True)
        # taped path: d(csr·W)/dW stays ROW-SPARSE (rows = features that
        # appear in the batch) — the reference's dot(csr.T, ograd)
        # row_sparse backward (`src/operator/tensor/dot-inl.h`
        # DotCsrTransDnsRspImpl), static-shape segment-sum form here
        from .. import autograd as _ag

        if _ag.is_recording() and not vec_rhs and not transpose_a and (
                getattr(rhs, "_marked", False)
                or getattr(rhs, "_entry", None) is not None):
            n_rows = rhs.shape[0]

            def vjp_fn(cots):
                (og,) = cots
                contrib = jnp.take(og, rows, axis=0) * vals[:, None]
                return (None, _ag._dedup_sparse_cot(cols, contrib, n_rows))

            ent = getattr(rhs, "_entry", None)
            entries = [None,
                       ("node", ent[0], ent[1]) if ent is not None
                       else ("leaf", rhs)]
            node = _ag.TapeNode("sparse_dot", vjp_fn, entries,
                               [(tuple(out.shape), out.dtype)])
            result._entry = (node, 0)
        return result
    if isinstance(lhs, NDArray) and not isinstance(lhs, BaseSparseNDArray) \
            and isinstance(rhs, CSRNDArray):
        # Dᵃ · Sᵇ = (Sᵇᵀ · Dᵃᵀ)ᵀ, with Dᵃᵀ = D when transpose_a else Dᵀ
        vec_lhs = lhs._data.ndim == 1
        ldata = lhs._data[None, :] if vec_lhs else lhs._data
        inner = NDArray(ldata if transpose_a and not vec_lhs else ldata.T,
                        ctx=lhs.ctx, _committed=True)
        out = dot(rhs, inner, transpose_a=not transpose_b)
        res = out._data.T
        if vec_lhs:
            res = res[0]
        return NDArray(res, ctx=lhs.ctx, _committed=True)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
        r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
        from .ndarray import imperative_invoke

        return imperative_invoke("dot", l, r, transpose_a=transpose_a,
                                 transpose_b=transpose_b)[0]
    from .ndarray import imperative_invoke

    return imperative_invoke("dot", lhs, rhs, transpose_a=transpose_a,
                             transpose_b=transpose_b)[0]


def retain(arr: RowSparseNDArray, row_ids) -> RowSparseNDArray:
    """Keep only `row_ids` (reference `_sparse_retain`,
    `src/operator/tensor/sparse_retain-inl.h`)."""
    jnp = _jnp()
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects row_sparse")
    rids = np.unique(np.asarray(_as_jax(row_ids)).astype(np.int32))
    stored = np.asarray(arr._aux[0]._data)
    # positions of requested rows inside the stored set
    pos = np.searchsorted(stored, rids)
    valid = (pos < len(stored))
    valid[valid] &= stored[pos[valid]] == rids[valid]
    keep_pos = pos[valid]
    data = jnp.take(arr._data, jnp.asarray(keep_pos), axis=0) \
        if len(keep_pos) else jnp.zeros((0,) + tuple(arr._data.shape[1:]),
                                        arr._data.dtype)
    return RowSparseNDArray(data, (jnp.asarray(stored[keep_pos]
                                               if len(keep_pos) else
                                               np.zeros((0,), np.int32)),),
                            arr.shape, ctx=arr.ctx)


def retain_rows_into(src: NDArray, row_ids, dst) -> None:
    """KVStore row_sparse_pull helper: gather `row_ids` rows of dense
    `src` into `dst` (row_sparse target gets exactly those rows; dense
    target gets them scattered into zeros)."""
    jnp = _jnp()
    rids_np = np.unique(np.asarray(_as_jax(row_ids)).astype(np.int32))
    rids = jnp.asarray(rids_np)
    rows = jnp.take(src._data, rids, axis=0)
    if isinstance(dst, RowSparseNDArray):
        dst._set_jax(rows)
        dst._aux = (NDArray(rids, ctx=dst.ctx),)
        dst._shape = tuple(src.shape)
    elif isinstance(dst, NDArray):
        out = jnp.zeros(src.shape, src._data.dtype).at[rids].set(rows)
        dst._set_jax(out)
    else:
        raise MXNetError("bad row_sparse_pull target %r" % type(dst))


def set_rows_into(rows: np.ndarray, data: np.ndarray, dst) -> None:
    """Write already-gathered rows (from a wire row-subset pull) into
    `dst`: a row_sparse target takes them verbatim; a dense target gets
    them scattered over its existing shape."""
    jnp = _jnp()
    if isinstance(dst, RowSparseNDArray):
        dst._set_jax(jnp.asarray(data))
        dst._aux = (NDArray(jnp.asarray(rows.astype(np.int32)),
                            ctx=dst.ctx),)
    elif isinstance(dst, NDArray):
        out = jnp.zeros(dst.shape, jnp.asarray(data).dtype)
        out = out.at[jnp.asarray(rows)].set(jnp.asarray(data))
        dst._set_jax(out)
    else:
        raise MXNetError("bad row_sparse_pull target %r" % type(dst))


def add(lhs, rhs):
    """elemwise_add with sparse-aware fast paths: rsp+rsp stays sparse
    (reference FComputeEx for add with row_sparse inputs)."""
    jnp = _jnp()
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError("shape mismatch")
        rows = np.union1d(np.asarray(lhs._aux[0]._data),
                          np.asarray(rhs._aux[0]._data)).astype(np.int32)
        out = jnp.zeros((len(rows),) + tuple(lhs._data.shape[1:]),
                        lhs._data.dtype)
        li = np.searchsorted(rows, np.asarray(lhs._aux[0]._data))
        ri = np.searchsorted(rows, np.asarray(rhs._aux[0]._data))
        if lhs._data.shape[0]:
            out = out.at[jnp.asarray(li)].add(lhs._data)
        if rhs._data.shape[0]:
            out = out.at[jnp.asarray(ri)].add(rhs._data)
        return RowSparseNDArray(out, (jnp.asarray(rows),), lhs.shape,
                                ctx=lhs.ctx)
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return l + r


elemwise_add = add
