"""Logging helpers — `mx.log.get_logger` (reference
`python/mxnet/log.py:80`).  Keeps the reference's colored-level
formatter idea in a simplified TTY-aware form."""
import logging
import sys

__all__ = ["get_logger", "getLogger"]

_COLORS = {"WARNING": "\x1b[0;33m", "ERROR": "\x1b[0;31m",
           "CRITICAL": "\x1b[0;35m", "DEBUG": "\x1b[0;34m",
           "INFO": "\x1b[0;32m"}


class _Formatter(logging.Formatter):
    def __init__(self, colored):
        self._colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def format(self, record):
        label = record.levelname[0]
        if self._colored:
            label = (_COLORS.get(record.levelname, "") + label
                     + "\x1b[0m")
        self._style._fmt = ("[%s %%(asctime)s %%(name)s] %%(message)s"
                            % label)
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None,
               level=logging.WARNING):
    """Logger with the framework's level-tagged format; file target when
    `filename` is given, colored on TTY stderr otherwise.  Handler and
    level install on FIRST init only (a later bare get_logger must not
    reset a level the user set), and the root logger (name=None) is
    returned untouched — installing a handler there would duplicate
    every propagating record and override unrelated libraries (same
    guard as the reference, `log.py:80`)."""
    logger = logging.getLogger(name)
    if name is None or getattr(logger, "_mxtpu_init", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        colored = False
    else:
        handler = logging.StreamHandler()
        colored = getattr(sys.stderr, "isatty", lambda: False)()
    handler.setFormatter(_Formatter(colored))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxtpu_init = True
    return logger


def getLogger(*args, **kwargs):
    """Deprecated alias kept for reference compatibility."""
    import warnings

    warnings.warn("getLogger is deprecated, use get_logger",
                  DeprecationWarning)
    return get_logger(*args, **kwargs)
