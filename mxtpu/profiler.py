"""Profiler — chrome://tracing dump + aggregate stats.

Reference: `src/profiler/profiler.h:256-304` (mode bitmask
kSymbolic|kImperative|kAPI|kMemory, DumpProfile), python surface
`python/mxnet/profiler.py:33-151` (set_config/set_state/pause/resume/
dump/dumps), aggregate tables `src/profiler/aggregate_stats.cc`, and the
engine's per-opr `ProfileOperator` wrap (`threaded_engine.h:336-347`).

TPU notes: host-side spans measure dispatch + (for jitted whole-graph
executors) device execution because the executor blocks on results it
returns lazily; set MXTPU_PROFILER_SYNC=1 to block after every op for
accurate per-op device times (the analog of the reference profiling
`NaiveEngine` mode).  For kernel-level device timing use jax.profiler
(XPlane) alongside — `start_xplane`/`stop_xplane` wrap it.

Autostart: MXTPU_PROFILER_AUTOSTART=1 (reference
MXNET_PROFILER_AUTOSTART, `docs/faq/env_var.md:156`).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "pause", "resume", "dump",
           "dumps", "Domain", "Task", "Frame", "Counter", "Marker",
           "start_xplane", "stop_xplane",
           "inc_stat", "get_stat", "stats", "reset_stats"]

_lock = threading.Lock()
_RUNNING = False
_PAUSED = False
_CONFIG = {
    "filename": "profile.json",
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "continuous_dump": False,
}
_EVENTS: List[Dict[str, Any]] = []
_AGG: Dict[str, List[float]] = {}
_START_TS = time.perf_counter()
_SYNC = os.environ.get("MXTPU_PROFILER_SYNC", "0") == "1"


def _now_us() -> float:
    return (time.perf_counter() - _START_TS) * 1e6


def set_config(**kwargs):
    """Configure (reference `profiler.py:33` set_config; accepts the
    reference's kwargs incl. profile_all)."""
    global _CONFIG
    if kwargs.pop("profile_all", False):
        for k in ("profile_symbolic", "profile_imperative",
                  "profile_memory", "profile_api"):
            _CONFIG[k] = True
    for k, v in kwargs.items():
        if k in _CONFIG:
            _CONFIG[k] = v
        elif k in ("profile_process", "aggregate_stats_filename"):
            pass
        else:
            raise MXNetError("unknown profiler config %r" % k)


def set_state(state_name: str = "stop"):
    global _RUNNING, _PAUSED
    if state_name not in ("run", "stop"):
        raise MXNetError("state must be 'run' or 'stop'")
    was = _RUNNING
    _RUNNING = state_name == "run"
    _PAUSED = False
    if was and not _RUNNING and _CONFIG["continuous_dump"]:
        dump()


def state() -> str:
    return "run" if _RUNNING else "stop"


def pause():
    global _PAUSED
    _PAUSED = True


def resume():
    global _PAUSED
    _PAUSED = False


def is_recording(kind: str = "imperative") -> bool:
    return _RUNNING and not _PAUSED and \
        _CONFIG.get("profile_" + kind, True)


def record_span(name: str, cat: str, ts_us: float, dur_us: float,
                tid: int = 0, args: Optional[Dict] = None):
    if not _RUNNING:
        return
    with _lock:
        _EVENTS.append({"name": name, "cat": cat, "ph": "X",
                        "ts": ts_us, "dur": dur_us, "pid": 0, "tid": tid,
                        **({"args": args} if args else {})})
        _AGG.setdefault(name, []).append(dur_us)


# -- compile-lifecycle stats ----------------------------------------------
# Always-on counters (a dict bump, not gated on set_state) so retrace
# regressions on the dispatch hot path are observable without turning
# the event profiler on: `mxtpu/compile_cache.py` ticks *_trace on
# every new shape signature, *_hit on reuse, *_aot_hit when a warmed
# executable serves the call, *_bucket_pad when a ragged batch was
# padded into an existing bucket.  tools/check_retrace.py gates CI on
# them.  The resilience layer ticks retry_*/fault_injected::<site>
# (mxtpu/resilience.py) and the elastic PS layer ticks elastic_*:
# elastic_failover / elastic_repush / elastic_promote (server shard
# failover), elastic_rerank (membership generation observed),
# elastic_rejoin (this worker re-registered into a running group),
# elastic_straggler_waits (a sync pull blocked > MXTPU_STRAGGLER_SEC),
# elastic_sched_reregister (heartbeat survived a scheduler restart).
# tools/check_elastic.py gates CI on the failover path.

_STATS: Dict[str, int] = {}


def inc_stat(name: str, delta: int = 1) -> int:
    with _lock:
        val = _STATS.get(name, 0) + delta
        _STATS[name] = val
    if _RUNNING and delta:
        record_counter("stat::" + name, float(val))
    return val


def get_stat(name: str) -> int:
    return _STATS.get(name, 0)


def stats() -> Dict[str, int]:
    """Snapshot of the compile-lifecycle counters."""
    with _lock:
        return dict(_STATS)


def reset_stats() -> None:
    with _lock:
        _STATS.clear()


def record_counter(name: str, value: float, ts_us: Optional[float] = None):
    if not _RUNNING:
        return
    with _lock:
        _EVENTS.append({"name": name, "ph": "C",
                        "ts": ts_us if ts_us is not None else _now_us(),
                        "pid": 0, "args": {name: value}})


class _Span(object):
    """Context manager measuring one span (engine ProfileOperator
    analog)."""

    __slots__ = ("name", "cat", "t0")

    def __init__(self, name: str, cat: str):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc):
        if _SYNC:
            try:
                import jax

                jax.effects_barrier()
            except Exception:
                pass
        record_span(self.name, self.cat, self.t0, _now_us() - self.t0,
                    tid=threading.get_ident() % 1000)
        if _CONFIG["profile_memory"]:
            _sample_memory()
        return False


_mem_counter = [0]


def _sample_memory():
    _mem_counter[0] += 1
    if _mem_counter[0] % 64:
        return
    try:
        import jax

        nbytes = sum(a.nbytes for a in jax.live_arrays())
        record_counter("device_mem_bytes", float(nbytes))
    except Exception:
        pass


def span(name: str, cat: str = "operator") -> _Span:
    return _Span(name, cat)


# -- user-facing objects (reference profiler.py Domain/Task/Frame/...) ----

class Domain(object):
    def __init__(self, name: str):
        self.name = name


class _Timed(object):
    def __init__(self, domain: Optional[Domain], name: str):
        self.name = (domain.name + "::" if domain else "") + name
        self._t0 = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is None:
            raise MXNetError("stop() before start()")
        record_span(self.name, type(self).__name__.lower(), self._t0,
                    _now_us() - self._t0)
        self._t0 = None


class Task(_Timed):
    def __init__(self, domain: Optional[Domain] = None, name: str = "task"):
        super().__init__(domain, name)


class Frame(_Timed):
    def __init__(self, domain: Optional[Domain] = None, name: str = "frame"):
        super().__init__(domain, name)


class Counter(object):
    def __init__(self, domain: Optional[Domain] = None,
                 name: str = "counter", value: float = 0):
        self.name = (domain.name + "::" if domain else "") + name
        self._value = value

    def set_value(self, value):
        self._value = value
        record_counter(self.name, float(value))

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)


class Marker(object):
    def __init__(self, domain: Optional[Domain] = None, name: str = "marker"):
        self.name = (domain.name + "::" if domain else "") + name

    def mark(self, scope: str = "process"):
        if not _RUNNING:
            return
        with _lock:
            _EVENTS.append({"name": self.name, "ph": "i", "ts": _now_us(),
                            "pid": 0, "tid": 0, "s": scope[0]})


# -- dumping ---------------------------------------------------------------

def dump(finished: bool = True, profile_process: str = "worker"):
    """Write accumulated events as chrome://tracing JSON (reference
    `DumpProfile`, `profiler.cc:166`)."""
    with _lock:
        payload = {"traceEvents": list(_EVENTS), "displayTimeUnit": "ms"}
        if finished:
            _EVENTS.clear()
    with open(_CONFIG["filename"], "w") as f:
        json.dump(payload, f)


def dumps(reset: bool = False, format: str = "table") -> str:
    """Aggregate stats table (reference MXAggregateProfileStatsPrint)."""
    with _lock:
        rows = []
        for name, durs in sorted(_AGG.items()):
            n = len(durs)
            total = sum(durs)
            rows.append((name, n, total, min(durs), max(durs), total / n))
        if reset:
            _AGG.clear()
    if format == "json":
        return json.dumps([{"name": r[0], "count": r[1], "total_us": r[2],
                            "min_us": r[3], "max_us": r[4], "avg_us": r[5]}
                           for r in rows])
    lines = ["%-48s %8s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for r in rows:
        lines.append("%-48s %8d %12.1f %12.1f %12.1f %12.1f" % r)
    return "\n".join(lines)


# -- XPlane bridge (device-level traces via jax.profiler) ------------------

def start_xplane(logdir: str = "/tmp/mxtpu_xplane"):
    import jax

    jax.profiler.start_trace(logdir)


def stop_xplane():
    import jax

    jax.profiler.stop_trace()


if os.environ.get("MXTPU_PROFILER_AUTOSTART",
                  os.environ.get("MXNET_PROFILER_AUTOSTART", "0")) == "1":
    set_state("run")
