"""Profiler — chrome://tracing dump + aggregate stats.

Reference: `src/profiler/profiler.h:256-304` (mode bitmask
kSymbolic|kImperative|kAPI|kMemory, DumpProfile), python surface
`python/mxnet/profiler.py:33-151` (set_config/set_state/pause/resume/
dump/dumps), aggregate tables `src/profiler/aggregate_stats.cc`, and the
engine's per-opr `ProfileOperator` wrap (`threaded_engine.h:336-347`).

TPU notes: host-side spans measure dispatch + (for jitted whole-graph
executors) device execution because the executor blocks on results it
returns lazily; set MXTPU_PROFILER_SYNC=1 to block after every op for
accurate per-op device times (the analog of the reference profiling
`NaiveEngine` mode).  The flag is read PER SPAN, so it can be flipped
mid-run; a span whose producer attached the op's results (``span.result``)
blocks on exactly those via ``jax.block_until_ready`` instead of the
global ``jax.effects_barrier``.  For kernel-level device timing use
jax.profiler (XPlane) alongside — `start_xplane`/`stop_xplane` wrap it.

Trace identity: every event is stamped with the REAL pid, `dump()`
emits chrome ``process_name``/``thread_name`` metadata rows (role+rank
from `mxtpu.telemetry`) and an ``otherData.epoch_origin_s`` wall-clock
origin, so per-role dumps from a distributed run merge into one
timeline via ``telemetry.merge_traces`` with clocks aligned.

Autostart: MXTPU_PROFILER_AUTOSTART=1 (reference
MXNET_PROFILER_AUTOSTART, `docs/faq/env_var.md:156`).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .base import MXNetError, getpid_cached

__all__ = ["set_config", "set_state", "state", "pause", "resume", "dump",
           "dumps", "Domain", "Task", "Frame", "Counter", "Marker",
           "start_xplane", "stop_xplane",
           "inc_stat", "get_stat", "set_stat", "max_stat", "stats",
           "reset_stats"]

# RLock: the telemetry flight recorder's signal handler reads stats()
# on whatever thread the signal lands on — possibly one already inside
# inc_stat's critical section (re-entry only reads; see telemetry.py)
_lock = threading.RLock()
_RUNNING = False
_PAUSED = False
_CONFIG = {
    "filename": "profile.json",
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "continuous_dump": False,
}
_EVENTS: List[Dict[str, Any]] = []
_AGG: Dict[str, List[float]] = {}
# the two origins are captured back-to-back: _START_TS anchors the
# relative event timestamps, _START_EPOCH records what wall-clock
# instant that zero corresponds to (the mergeable-trace contract)
_START_TS = time.perf_counter()
_START_EPOCH = time.time()


def _now_us() -> float:
    return (time.perf_counter() - _START_TS) * 1e6


def _sync_enabled() -> bool:
    """MXTPU_PROFILER_SYNC, read per-span (NOT latched at import) so a
    run can flip into accurate-device-timing mode on the fly."""
    return os.environ.get("MXTPU_PROFILER_SYNC", "0") == "1"


def set_config(**kwargs):
    """Configure (reference `profiler.py:33` set_config; accepts the
    reference's kwargs incl. profile_all)."""
    global _CONFIG
    if kwargs.pop("profile_all", False):
        for k in ("profile_symbolic", "profile_imperative",
                  "profile_memory", "profile_api"):
            _CONFIG[k] = True
    for k, v in kwargs.items():
        if k in _CONFIG:
            _CONFIG[k] = v
        elif k in ("profile_process", "aggregate_stats_filename"):
            pass
        else:
            raise MXNetError("unknown profiler config %r" % k)


def set_state(state_name: str = "stop"):
    global _RUNNING, _PAUSED
    if state_name not in ("run", "stop"):
        raise MXNetError("state must be 'run' or 'stop'")
    was = _RUNNING
    _RUNNING = state_name == "run"
    _PAUSED = False
    if was and not _RUNNING and _CONFIG["continuous_dump"]:
        dump()


def state() -> str:
    return "run" if _RUNNING else "stop"


def pause():
    global _PAUSED
    _PAUSED = True


def resume():
    global _PAUSED
    _PAUSED = False


def is_recording(kind: str = "imperative") -> bool:
    return _RUNNING and not _PAUSED and \
        _CONFIG.get("profile_" + kind, True)


def record_span(name: str, cat: str, ts_us: float, dur_us: float,
                tid: int = 0, args: Optional[Dict] = None):
    if not _RUNNING or _PAUSED:
        return
    with _lock:
        _EVENTS.append({"name": name, "cat": cat, "ph": "X",
                        "ts": ts_us, "dur": dur_us, "pid": getpid_cached(),
                        "tid": tid,
                        **({"args": args} if args else {})})
        _AGG.setdefault(name, []).append(dur_us)


# -- always-on stats -------------------------------------------------------
# Counters (a dict bump, not gated on set_state) so hot-path
# regressions are observable without turning the event profiler on.
# The full counter-namespace catalog (compile-lifecycle *_trace/*_hit,
# resilience retry_*/fault_injected::<site>, elastic_*, telemetry_*)
# lives in `docs/observability.md`.

_STATS: Dict[str, int] = {}


def inc_stat(name: str, delta: int = 1) -> int:
    with _lock:
        val = _STATS.get(name, 0) + delta
        _STATS[name] = val
    if _RUNNING and delta:
        record_counter("stat::" + name, float(val))
    return val


def get_stat(name: str) -> int:
    return _STATS.get(name, 0)


def set_stat(name: str, value: int) -> None:
    """Set an absolute gauge value (e.g. ``step_time_us_last``) —
    counters use :func:`inc_stat`, gauges this."""
    with _lock:
        _STATS[name] = int(value)


def max_stat(name: str, value: int) -> None:
    """Raise a watermark gauge (e.g. ``device_mem_watermark_bytes``)
    to ``value`` if it is higher."""
    with _lock:
        if int(value) > _STATS.get(name, 0):
            _STATS[name] = int(value)


def stats() -> Dict[str, int]:
    """Snapshot of the compile-lifecycle counters."""
    with _lock:
        return dict(_STATS)


def reset_stats() -> None:
    with _lock:
        _STATS.clear()


def record_counter(name: str, value: float, ts_us: Optional[float] = None):
    if not _RUNNING or _PAUSED:
        return
    with _lock:
        _EVENTS.append({"name": name, "ph": "C",
                        "ts": ts_us if ts_us is not None else _now_us(),
                        "pid": getpid_cached(), "args": {name: value}})


class _Span(object):
    """Context manager measuring one span (engine ProfileOperator
    analog).  A producer may attach the span's device results via
    ``span.result = <jax arrays>``; under MXTPU_PROFILER_SYNC the exit
    then blocks on exactly those (``jax.block_until_ready``) for a
    true synchronous device timing, falling back to the global
    ``jax.effects_barrier`` when nothing was attached."""

    __slots__ = ("name", "cat", "t0", "result")

    def __init__(self, name: str, cat: str):
        self.name = name
        self.cat = cat
        self.result = None

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc):
        if _sync_enabled():
            try:
                import jax

                if self.result is not None:
                    jax.block_until_ready(self.result)
                else:
                    jax.effects_barrier()
            except Exception:
                pass
        record_span(self.name, self.cat, self.t0, _now_us() - self.t0,
                    tid=threading.get_ident() % 1000)
        if _CONFIG["profile_memory"]:
            _sample_memory()
        return False


_mem_counter = [0]


def _sample_memory():
    _mem_counter[0] += 1
    if _mem_counter[0] % 64:
        return
    try:
        import jax

        nbytes = sum(a.nbytes for a in jax.live_arrays())
        record_counter("device_mem_bytes", float(nbytes))
    except Exception:
        pass


def span(name: str, cat: str = "operator") -> _Span:
    return _Span(name, cat)


# -- user-facing objects (reference profiler.py Domain/Task/Frame/...) ----

class Domain(object):
    def __init__(self, name: str):
        self.name = name


class _Timed(object):
    def __init__(self, domain: Optional[Domain], name: str):
        self.name = (domain.name + "::" if domain else "") + name
        self._t0 = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is None:
            raise MXNetError("stop() before start()")
        record_span(self.name, type(self).__name__.lower(), self._t0,
                    _now_us() - self._t0)
        self._t0 = None


class Task(_Timed):
    def __init__(self, domain: Optional[Domain] = None, name: str = "task"):
        super().__init__(domain, name)


class Frame(_Timed):
    def __init__(self, domain: Optional[Domain] = None, name: str = "frame"):
        super().__init__(domain, name)


class Counter(object):
    def __init__(self, domain: Optional[Domain] = None,
                 name: str = "counter", value: float = 0):
        self.name = (domain.name + "::" if domain else "") + name
        self._value = value

    def set_value(self, value):
        self._value = value
        record_counter(self.name, float(value))

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)


class Marker(object):
    def __init__(self, domain: Optional[Domain] = None, name: str = "marker"):
        self.name = (domain.name + "::" if domain else "") + name

    def mark(self, scope: str = "process"):
        if not _RUNNING or _PAUSED:
            return
        with _lock:
            _EVENTS.append({"name": self.name, "ph": "i", "ts": _now_us(),
                            "pid": getpid_cached(), "tid": 0, "s": scope[0]})


# -- dumping ---------------------------------------------------------------

def dump(finished: bool = True, profile_process: str = "worker"):
    """Write accumulated events as chrome://tracing JSON (reference
    `DumpProfile`, `profiler.cc:166`).

    The dump is self-describing for cross-process merging: events
    carry the real pid, a ``process_name`` metadata row names this
    role+rank, and ``otherData.epoch_origin_s`` records the wall-clock
    instant of ts=0 so `mxtpu.telemetry.merge_traces` can align
    per-role dumps onto one timeline."""
    try:
        from . import telemetry as _tel

        ident = _tel.identity()
    except Exception:
        ident = {"role": "local", "rank": 0, "pid": os.getpid()}
    pid = os.getpid()
    meta = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "%s%d (pid %d)"
                  % (ident["role"], ident["rank"], pid)}},
    ]
    # name the thread rows that actually hold events (spans record
    # tid = get_ident() % 1000, so label those, marking this thread —
    # the dumper, almost always the dispatch thread — as such)
    main_tid = threading.get_ident() % 1000
    with _lock:
        seen_tids = {e.get("tid", 0) for e in _EVENTS}
        for tid in sorted(seen_tids):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid,
                         "args": {"name": "dispatch" if tid == main_tid
                                  else "thread-%d" % tid}})
        payload = {"traceEvents": meta + list(_EVENTS),
                   "displayTimeUnit": "ms",
                   "otherData": {"epoch_origin_s": _START_EPOCH,
                                 "role": ident["role"],
                                 "rank": ident["rank"], "pid": pid}}
        if finished:
            _EVENTS.clear()
    with open(_CONFIG["filename"], "w") as f:
        json.dump(payload, f)


def dumps(reset: bool = False, format: str = "table") -> str:
    """Aggregate stats table (reference MXAggregateProfileStatsPrint)."""
    with _lock:
        rows = []
        for name, durs in sorted(_AGG.items()):
            n = len(durs)
            total = sum(durs)
            rows.append((name, n, total, min(durs), max(durs), total / n))
        if reset:
            _AGG.clear()
    if format == "json":
        return json.dumps([{"name": r[0], "count": r[1], "total_us": r[2],
                            "min_us": r[3], "max_us": r[4], "avg_us": r[5]}
                           for r in rows])
    lines = ["%-48s %8s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for r in rows:
        lines.append("%-48s %8d %12.1f %12.1f %12.1f %12.1f" % r)
    return "\n".join(lines)


# -- XPlane bridge (device-level traces via jax.profiler) ------------------

def start_xplane(logdir: str = "/tmp/mxtpu_xplane"):
    import jax

    jax.profiler.start_trace(logdir)


def stop_xplane():
    import jax

    jax.profiler.stop_trace()


if os.environ.get("MXTPU_PROFILER_AUTOSTART",
                  os.environ.get("MXNET_PROFILER_AUTOSTART", "0")) == "1":
    set_state("run")
