"""Unified telemetry: event ring, per-step metrics, flight recorder.

The profiler (`mxtpu/profiler.py`) answers "where did the time go in
THIS process while I was watching"; this module answers the production
questions around it: what was every role doing just before the job
wedged, how fast is each rank actually stepping, and what does the
WHOLE cluster look like from one place.  Three pieces, one identity
(role / rank / pid / wall-clock epoch timestamps) shared by all:

  * **Structured event log** — a bounded in-memory ring of typed
    records (:data:`EVENT_KINDS`): training steps, XLA compiles,
    kvstore rounds, retries, failovers, checkpoints, membership
    changes, monitor stats.  Producers live in ``executor.py``,
    ``cached_op.py``, ``fused_train.py``, ``gluon/trainer.py``,
    ``module/module.py``, ``kvstore.py``, ``_ps.py``,
    ``resilience.py``, ``compile_cache.py`` and ``monitor.py``.
    Every record carries epoch (``time.time()``) timestamps plus the
    step / kvstore-round correlation ids, so records from different
    processes merge on a common axis.

  * **Cross-process aggregation** — every PS role ships its counter
    snapshot + recent events to the scheduler on the existing
    heartbeat channel (`_ps._start_heartbeat`); ``kv.telemetry()``
    returns the scheduler's merged per-node view, and
    ``tools/launch.py --telemetry-dir`` makes each role write a final
    ``telemetry_<role><rank>.json`` which :func:`merge_dir` folds into
    ONE chrome trace (clocks aligned via the epoch timestamps) and a
    cluster counter view (per-rank step time, straggler spread,
    retry/failover totals).

  * **Flight recorder** — :func:`dump_flight` writes the ring + the
    counter snapshot + all-thread stacks as
    ``flight_<role><rank>.json``.  Triggers: SIGTERM/SIGQUIT
    (:func:`install_flight_recorder`), unhandled exceptions
    (sys/threading excepthook), a dist kvstore timeout
    (``MXTPU_KVSTORE_TIMEOUT`` expiry in ``_ps._Client``), and the
    ``MXTPU_MAX_BAD_STEPS`` abort.  A SIGKILLed node cannot dump its
    own corpse, so the scheduler writes a POSTHUMOUS flight file from
    the node's last heartbeat-shipped snapshot when it declares the
    node dead (:func:`dump_flight_for`) — a ``check_elastic``-style
    kill still leaves a diagnosable record naming the dead rank's
    last round.

Always-on and cheap: ``MXTPU_TELEMETRY=0`` opts out entirely (every
producer call is then one bool check); the ring is bounded
(``MXTPU_TELEMETRY_RING``, default 512) and the per-step path is a few
dict operations with NO device synchronization.  The device-memory
watermark samples ``jax.live_arrays()`` only every
``MXTPU_TELEMETRY_MEMSAMPLE`` (64) steps.  Measured overhead is <1%
on the training hot paths (`docs/observability.md`).

Event record schema (all values JSON-safe scalars)::

    {"kind": <EVENT_KINDS>, "ts": <epoch seconds>,
     "role": "worker", "rank": 0, "pid": 12345,
     "step": <step id>?, "round": <kvstore round>?, ...payload}

See `docs/observability.md` for the full per-kind payload catalog.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from .base import getenv, getenv_bool, getpid_cached

__all__ = [
    "EVENT_KINDS",
    "GAUGE_STATS",
    "enabled",
    "enable",
    "set_identity",
    "identity",
    "record",
    "record_step",
    "record_input_wait",
    "input_wait",
    "current_step",
    "events",
    "stat_rollup",
    "health_rollup",
    "perf_rollup",
    "clear",
    "metrics",
    "snapshot",
    "hb_payload",
    "aggregate_stats",
    "dump_flight",
    "dump_flight_for",
    "install_flight_recorder",
    "uninstall_flight_recorder",
    "flush",
    "merge_dir",
    "merge_traces",
    "Speedometer",
    "Histogram",
    "histogram",
    "histograms",
    "register_metrics_provider",
    "unregister_metrics_provider",
]

#: The typed record vocabulary.  ``step`` = one (or K fused) training
#: steps; ``compile`` = a new XLA program is being built; ``kvstore`` =
#: a worker-side push/pull; ``kvstore_round`` = a server applied a
#: completed sync round; ``retry`` = a resilience chokepoint retried;
#: ``failover`` = elastic server failover; ``membership`` = group
#: change (death declared / re-rank / rejoin); ``checkpoint`` = a
#: manifest committed; ``monitor`` = a Monitor tensor stat; ``timeout``
#: = a dist kvstore exchange expired; ``flight`` = a flight dump fired.
#: (``anomaly`` = the training-health layer, `mxtpu/health.py`,
#: detected a non-finite step, loss/grad spike, step-time regression
#: or OOM; ``tensor_stats`` = one in-graph per-layer grad/param-norm
#: sample at the ``MXTPU_HEALTH_STATS_EVERY`` cadence, rendered as
#: chrome-trace counter tracks by :func:`merge_dir`.)
#: (``perf`` = an `mx.perf` sampled device-sync point: per-program
#: host_dispatch/device_compute/wall spans + MFU when known, rendered
#: as chrome-trace counter tracks by :func:`merge_dir`.)
#: (``span`` = one finished `mx.tracing` causal span: trace/span/parent
#: ids + name + ``dur_s``, ts = the span's END like ``step`` records;
#: :func:`merge_dir` renders them as X spans and has
#: ``tracing.stitch`` join cross-process traces with flow events.)
#: (``tuning`` = one `mx.tune` lifecycle point: a measured trial
#: (``action="trial"``, trial id + score + config), a finished search
#: session (``action="session"``), or a DB config auto-applied at
#: bind/hybridize/add_model (``action="apply"``, with the same
#: provenance string `mx.inspect` stamps on program records).)
#: (``op_profile`` = one `mx.xprof` per-op attribution attached to a
#: program: acquisition source (xplane/replay), op count, per-step
#: device time, per-op-class rollup and the top sink's name/class/
#: share — how cluster.json and ``tools/dash.py`` name each rank's
#: dominant device-time sink.)
EVENT_KINDS = ("step", "compile", "kvstore", "kvstore_round", "retry",
               "failover", "membership", "checkpoint", "monitor",
               "timeout", "flight", "anomaly", "tensor_stats", "serve",
               "reshard", "perf", "span", "tuning", "resume",
               "op_profile")

#: ``profiler.stats()`` keys that are point-in-time gauges, not
#: additive counters: cluster aggregation takes their MAX, and counter
#: reconciliation (`tools/check_telemetry.py`) excludes them from the
#: sum-of-roles check.
GAUGE_STATS = ("step_time_us_last", "device_mem_watermark_bytes",
               "kvstore_round_last", "input_wait_us_last",
               "serve_queue_depth", "serve_inflight",
               "serve_batch_occupancy_pct", "serve_max_batch",
               "perf_host_dispatch_us_last",
               "perf_device_compute_us_last", "perf_input_wait_us_last",
               "perf_optimizer_us_last", "perf_collective_us_last",
               "obs_sample_wall_us_last")

# RLock, NOT Lock: the flight recorder's signal handler snapshots
# state on whatever thread the signal lands on — if that thread was
# inside record_step()'s critical section, a non-reentrant lock would
# deadlock the handler against itself and turn a clean SIGTERM into a
# wedge.  Re-entry only ever READS, so mid-update values are safe.
_lock = threading.RLock()

_ENABLED = getenv_bool("MXTPU_TELEMETRY", True)
_RING_SIZE = max(16, int(getenv("MXTPU_TELEMETRY_RING", "512") or 512))
_MEM_SAMPLE_EVERY = max(1, int(getenv("MXTPU_TELEMETRY_MEMSAMPLE", "64")
                               or 64))
# the live_arrays fallback walks every device buffer (milliseconds on
# a big process): never more often than this many seconds
_MEM_MIN_INTERVAL = float(getenv("MXTPU_TELEMETRY_MEM_INTERVAL", "10")
                          or 10)
_HB_EVENTS = max(0, int(getenv("MXTPU_TELEMETRY_HB_EVENTS", "64") or 64))

_RING: collections.deque = collections.deque(maxlen=_RING_SIZE)

# anchor for telling THIS run's flight records apart from leftovers in
# a reused --telemetry-dir (files older than process start are stale)
_START_TIME = time.time()

_IDENTITY = {
    "role": getenv("MXTPU_ROLE", getenv("DMLC_ROLE", "local")) or "local",
    "rank": 0,
}

# per-step metric accumulators (under _lock)
_METRICS = {"steps": 0, "examples": 0.0, "dt_sum": 0.0, "dt_last": 0.0,
            "last_t": None, "nonfinite": 0, "mem_watermark": 0,
            "input_waits": 0, "input_wait_sum": 0.0,
            "input_wait_last": 0.0}


def enabled() -> bool:
    """Telemetry on?  ``MXTPU_TELEMETRY=0`` opts out at import."""
    return _ENABLED


def enable(on: bool = True) -> None:
    """Flip telemetry at runtime (tests / embedding)."""
    global _ENABLED
    _ENABLED = bool(on)


def set_identity(role: Optional[str] = None,
                 rank: Optional[int] = None) -> None:
    """Stamp this process's role/rank into every future record.  The
    PS layer calls this as soon as the scheduler assigns a rank (and
    again on elastic re-rank)."""
    with _lock:
        if role is not None:
            _IDENTITY["role"] = str(role)
        if rank is not None:
            _IDENTITY["rank"] = int(rank)


def identity() -> Dict[str, Any]:
    """``{"role", "rank", "pid"}`` of this process (the cached pid is
    refreshed on fork, so dataloader workers stamp their own)."""
    with _lock:
        return {"role": _IDENTITY["role"], "rank": _IDENTITY["rank"],
                "pid": getpid_cached()}


def record(kind: str, **fields) -> Optional[Dict[str, Any]]:
    """Append one typed record to the ring.  One bool check when
    telemetry is off; a dict build + deque append when on — safe on
    hot paths.  ``fields`` must be JSON-safe scalars.  Returns the
    record dict (held by reference in the ring) so a producer may
    BACKFILL scalar fields it created eagerly — `mx.inspect` fills
    ``flops``/``peak_bytes`` on ``compile`` events once its lazy
    analysis runs (assignment to pre-existing keys only, so a
    concurrent JSON dump never sees the dict change size)."""
    if not _ENABLED:
        return None
    ev = {"kind": kind, "ts": time.time(), "pid": getpid_cached(),
          "role": _IDENTITY["role"], "rank": _IDENTITY["rank"]}
    for k, v in fields.items():
        if v is not None:
            ev[k] = v
    _RING.append(ev)
    return ev


def record_step(batch_size: int = 0, n: int = 1,
                duration: Optional[float] = None,
                skipped: bool = False, site: str = "train",
                grad_norm: Optional[float] = None,
                skipped_n: Optional[int] = None) -> int:
    """Account one training step (or ``n`` fused steps) and emit a
    ``step`` record.  ``duration`` defaults to the wall time since the
    previous call — the full iteration time including data/forward/
    backward, measured with NO device sync.  ``skipped`` marks a
    non-finite-grad step the trainer dropped (``skipped_n`` = how many
    of the ``n`` fused steps were dropped); ``grad_norm`` attaches the
    global gradient norm when a producer already has it in hand (the
    health layer's one-program check), making skipped-step bursts
    diagnosable from the flight recorder.  Returns the step id (the
    correlation id monitor/kvstore records share)."""
    if not _ENABLED:
        return 0
    now = time.monotonic()
    with _lock:
        last = _METRICS["last_t"]
        _METRICS["last_t"] = now
        if duration is None:
            duration = (now - last) if last is not None else 0.0
        _METRICS["steps"] += n
        step_id = _METRICS["steps"]
        _METRICS["examples"] += float(batch_size) * n
        _METRICS["dt_sum"] += duration
        _METRICS["dt_last"] = duration / max(1, n)
        if skipped_n is None:
            skipped_n = n if skipped else 0
        if skipped_n:
            _METRICS["nonfinite"] += skipped_n
        dt_last = _METRICS["dt_last"]
    from . import profiler as _prof

    _prof.inc_stat("telemetry_steps", n)
    if batch_size:
        _prof.inc_stat("telemetry_examples", int(batch_size) * n)
    _prof.set_stat("step_time_us_last", int(dt_last * 1e6))
    record("step", step=step_id, n=n, batch=int(batch_size),
           dur_s=round(duration, 6), site=site,
           skipped=True if skipped_n else None,
           skipped_n=skipped_n if skipped_n and n > 1 else None,
           grad_norm=round(float(grad_norm), 6)
           if grad_norm is not None else None)
    # step-time straggler/regression watchdog (mxtpu/health.py): a
    # deque append + cached-median compare — stays on the <10us/step
    # always-on budget tools/check_health.py asserts
    from . import health as _health

    _health.observe_step(step_id, dt_last, site=site)
    if step_id == n or (step_id % _MEM_SAMPLE_EVERY) < n:
        _sample_device_mem()
    return step_id


def record_input_wait(dur_s: float) -> None:
    """Account one host-input wait: the wall time the training loop
    spent BLOCKED waiting for the data pipeline to hand over the next
    batch (DataLoader / DataIter ``__next__``).  Always-on gauge
    (``input_wait_us_last`` in `profiler.stats()`) + running totals in
    :func:`metrics` — this is what attributes an input-bound step-time
    gap (the 911us/step dispatch gap in BENCH_r05) to the pipeline
    instead of the device.  Producers that can NEST (a DataLoader
    whose fetch drives an inner DataIter — both used to stamp the same
    wait, double-counting it) should wrap the fetch in
    :func:`input_wait` instead, which records only at the outermost
    level.  Also feeds the `mx.perf` phase schema as ``input_wait``."""
    if not _ENABLED:
        return
    with _lock:
        _METRICS["input_waits"] += 1
        _METRICS["input_wait_sum"] += dur_s
        _METRICS["input_wait_last"] = dur_s
    from . import profiler as _prof

    _prof.set_stat("input_wait_us_last", int(dur_s * 1e6))
    from . import perf as _perf

    _perf.note_phase("input_wait", dur_s)


_INPUT_WAIT_TLS = threading.local()


class _InputWait(object):
    """Re-entrancy-guarded input-wait scope (see :func:`input_wait`).
    A plain class, not ``contextmanager``: this sits on the per-batch
    hot path and a generator frame per batch is measurable there."""

    __slots__ = ("_outer", "_t0")

    def __enter__(self):
        depth = getattr(_INPUT_WAIT_TLS, "depth", 0)
        _INPUT_WAIT_TLS.depth = depth + 1
        self._outer = depth == 0
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _INPUT_WAIT_TLS.depth = getattr(_INPUT_WAIT_TLS, "depth", 1) - 1
        # only the OUTERMOST scope on this thread records: a DataLoader
        # wrapping a DataIter (or any nested iterator stack) counts the
        # wait ONCE, at the layer the training loop actually blocked on
        if self._outer and exc[0] is None:
            record_input_wait(time.perf_counter() - self._t0)
        return False


def input_wait() -> _InputWait:
    """Context manager measuring one host-input wait with a
    thread-local nesting guard: nested scopes (outer ``DataLoader``
    fetch driving an inner ``DataIter.__next__``) record nothing —
    only the outermost records, so `input_wait_frac` can never
    double-count one wall-clock wait::

        with telemetry.input_wait():
            batch = next(source)
    """
    return _InputWait()


_last_mem_sample = [0.0]


def _sample_device_mem() -> None:
    """Device-memory watermark — sampled every
    ``MXTPU_TELEMETRY_MEMSAMPLE`` steps, never per step.  Prefers the
    runtime's O(1) ``device.memory_stats()`` (real allocator numbers
    on TPU); the ``jax.live_arrays()`` fallback walks every buffer
    (milliseconds on a large process), so it is additionally
    rate-limited to once per ``MXTPU_TELEMETRY_MEM_INTERVAL``
    seconds."""
    try:
        import jax

        nbytes = 0
        for dev in jax.local_devices():
            try:
                stats = getattr(dev, "memory_stats", lambda: None)()
            except Exception:
                stats = None  # unimplemented on some PJRT plugins:
                # treat like a None return so the fallback still runs
            if not stats:
                nbytes = 0
                break
            nbytes += int(stats.get("peak_bytes_in_use",
                                    stats.get("bytes_in_use", 0)))
        if not nbytes:
            now = time.monotonic()
            if now - _last_mem_sample[0] < _MEM_MIN_INTERVAL:
                return
            _last_mem_sample[0] = now
            nbytes = sum(int(a.nbytes) for a in jax.live_arrays())
    except Exception:
        return
    with _lock:
        if nbytes > _METRICS["mem_watermark"]:
            _METRICS["mem_watermark"] = nbytes
    from . import profiler as _prof

    _prof.max_stat("device_mem_watermark_bytes", nbytes)
    try:
        from . import hbm as _hbm

        _hbm.observe_used(nbytes)
    except Exception:
        pass


def current_step() -> int:
    """The latest COMPLETED step id (0 before any step).  Producers
    stamping in-flight work (a push, a compile) therefore tag it with
    the previous step's id — the documented join rule is "events of
    step N carry step == N-1" (`docs/observability.md`)."""
    with _lock:
        return _METRICS["steps"]


def events(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Snapshot of the ring (oldest first), optionally one kind."""
    evs = list(_RING)
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


def clear() -> None:
    """Drop all ring records and reset the step metrics (tests).
    Registered histograms are reset in place (the registry itself —
    and any metrics providers — survive, matching counter behavior)."""
    _RING.clear()
    with _lock:
        _METRICS.update(steps=0, examples=0.0, dt_sum=0.0, dt_last=0.0,
                        last_t=None, nonfinite=0, mem_watermark=0,
                        input_waits=0, input_wait_sum=0.0,
                        input_wait_last=0.0)
        for h in _HISTOGRAMS.values():
            h.reset()


# ---------------------------------------------------------------------------
# Streaming percentile histograms
# ---------------------------------------------------------------------------

class Histogram(object):
    """Bounded streaming percentile histogram over log-spaced buckets.

    Fixed memory (one int per bucket, ~170 buckets at the defaults),
    O(1) :meth:`record`, thread-safe.  Buckets grow geometrically by
    ``10**(1/bins_per_decade)`` from ``low`` to ``high`` (values
    outside clamp into the under/overflow buckets), so any quantile is
    answered within ~``(growth-1)/2`` relative error — ±7% at the
    default 16 bins/decade, plenty for latency SLOs where the question
    is "is p99 under 200ms", not "is p99 198.3ms or 198.4ms".

    This is the serving SLO primitive: `mx.serve` keeps one per model
    for request latency (p50/p95/p99 surfaced via :func:`metrics`),
    and ``benchmark/python/bench_serving.py``'s closed-loop clients
    feed the same class, so server-side and client-side latency
    distributions are directly comparable.

    Use the module-level :func:`histogram` get-or-create registry to
    have a histogram's :meth:`snapshot` ride along in
    :func:`metrics()["histograms"]` (and therefore in heartbeat
    snapshots and ``telemetry_*.json`` dumps) automatically.
    """

    def __init__(self, low: float = 1e-6, high: float = 1e4,
                 bins_per_decade: int = 16):
        import math

        if not (0 < low < high):
            raise ValueError("need 0 < low < high, got %r, %r"
                             % (low, high))
        self.low = float(low)
        self.high = float(high)
        self._log_growth = math.log(10.0) / max(1, int(bins_per_decade))
        # bucket 0 = underflow (<= low); last = overflow (>= high)
        self.nbins = int(math.ceil(
            math.log(high / low) / self._log_growth)) + 2
        self._counts = [0] * self.nbins
        # RLock for the same reason as the module _lock above: a
        # flight-recorder signal landing inside record() must be able
        # to snapshot() on the same thread (re-entry only reads, so a
        # mid-update count is an acceptable crash-dump approximation)
        self._hlock = threading.RLock()
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def reset(self) -> None:
        with self._hlock:
            self._counts = [0] * self.nbins
            self.count = 0
            self.total = 0.0
            self.vmin = float("inf")
            self.vmax = float("-inf")

    def _index(self, v: float) -> int:
        import math

        if v <= self.low:
            return 0
        if math.isinf(v):  # int(log(inf)) would raise OverflowError
            return self.nbins - 1
        i = int(math.log(v / self.low) / self._log_growth) + 1
        return i if i < self.nbins else self.nbins - 1

    def record(self, value: float) -> None:
        v = float(value)
        if v != v:  # NaN would poison min/max and land nowhere sane
            return
        i = self._index(v)
        if v == float("inf"):
            v = self.high  # overflow bucket; keep total/vmax finite
        elif v == float("-inf"):
            v = self.low   # underflow bucket; keep total/vmin finite
        with self._hlock:
            self._counts[i] += 1
            self.count += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold another histogram of the SAME bucket layout into this
        one (per-worker client histograms -> one run view)."""
        if (other.low, other._log_growth, other.nbins) != \
                (self.low, self._log_growth, self.nbins):
            raise ValueError("cannot merge histograms with different "
                             "bucket layouts")
        # canonical lock order: a.merge(b) racing b.merge(a) would
        # otherwise hold one lock each and deadlock waiting on the other
        first, second = (self, other) if id(self) <= id(other) \
            else (other, self)
        with first._hlock:
            with second._hlock:
                for i, c in enumerate(other._counts):
                    self._counts[i] += c
                self.count += other.count
                self.total += other.total
                self.vmin = min(self.vmin, other.vmin)
                self.vmax = max(self.vmax, other.vmax)
        return self

    def _quantile_of(self, counts, n: int, q: float,
                     vmin: Optional[float] = None,
                     vmax: Optional[float] = None) -> float:
        """q-quantile over an arbitrary bucket-count vector of THIS
        histogram's layout (shared by the cumulative :meth:`quantile`
        and the windowed :meth:`interval`): the geometric midpoint of
        the bucket holding the rank, clamped into [vmin, vmax] when
        given.  0.0 when the vector is empty."""
        import math

        if n <= 0:
            return 0.0
        rank = min(n - 1, max(0, int(math.ceil(q * n)) - 1))
        acc = 0
        idx = self.nbins - 1
        for i, c in enumerate(counts):
            acc += c
            if acc > rank:
                idx = i
                break
        if idx == 0:
            est = self.low
        else:
            # bucket idx spans [low*g^(idx-1), low*g^idx)
            est = self.low * math.exp(self._log_growth * (idx - 0.5))
        if vmin is not None:
            est = max(est, vmin)
        if vmax is not None:
            est = min(est, vmax)
        return est

    def quantile(self, q: float) -> float:
        """The q-quantile (0..1) as the geometric midpoint of the
        bucket holding that rank, clamped to the observed [min, max].
        0.0 when empty."""
        with self._hlock:
            counts = list(self._counts)
            n = self.count
            vmin, vmax = self.vmin, self.vmax
        return self._quantile_of(counts, n, q, vmin, vmax)

    def state(self) -> tuple:
        """Opaque cumulative state for :meth:`interval` — take one,
        hold it, and the next ``interval(prev_state)`` call answers
        "what were the percentiles BETWEEN the two samples"."""
        with self._hlock:
            return (tuple(self._counts), self.count, self.total)

    def interval(self, prev: Optional[tuple] = None):
        """WINDOWED snapshot: percentiles of only the values recorded
        since ``prev`` (a state returned by :meth:`state` or a prior
        ``interval`` call).  ``prev=None`` means "since the
        beginning".  Returns ``(snapshot_dict, new_state)`` where the
        dict carries per-window ``count/sum/avg/p50/p95/p99`` — the
        time-series row primitive (`mx.obs` sample rows show
        per-interval latency, not lifetime-cumulative values).  A
        :meth:`reset` inside the window (cumulative counts went
        backwards) degrades gracefully to "everything currently
        recorded".  Interval quantiles clamp to the bucket range, not
        a per-window min/max (not tracked per window)."""
        with self._hlock:
            cur = (tuple(self._counts), self.count, self.total)
        if (prev is None or len(prev) != 3
                or len(prev[0]) != len(cur[0])):
            prev = ((0,) * len(cur[0]), 0, 0.0)
        counts = [a - b for a, b in zip(cur[0], prev[0])]
        n = cur[1] - prev[1]
        tot = cur[2] - prev[2]
        if n < 0 or any(c < 0 for c in counts):
            counts, n, tot = list(cur[0]), cur[1], cur[2]
        snap = {"count": n, "sum": tot,
                "avg": tot / n if n else 0.0,
                "p50": self._quantile_of(counts, n, 0.50),
                "p95": self._quantile_of(counts, n, 0.95),
                "p99": self._quantile_of(counts, n, 0.99)}
        return snap, cur

    def percentiles(self) -> Dict[str, float]:
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe summary: count/sum/avg/min/max + p50/p95/p99."""
        with self._hlock:
            n, tot = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        out = {"count": n, "sum": tot, "avg": tot / n if n else 0.0,
               "min": vmin if n else 0.0, "max": vmax if n else 0.0}
        out.update(self.percentiles())
        return out


_HISTOGRAMS: Dict[str, Histogram] = {}


def histogram(name: str, low: float = 1e-6, high: float = 1e4,
              bins_per_decade: int = 16) -> Histogram:
    """Get-or-create the registered histogram ``name``.  Registered
    histograms appear in :func:`metrics()["histograms"]` and reset
    with :func:`clear`."""
    with _lock:
        h = _HISTOGRAMS.get(name)
        if h is None:
            h = _HISTOGRAMS[name] = Histogram(low, high, bins_per_decade)
        return h


def histograms() -> Dict[str, Dict[str, Any]]:
    """Snapshots of every registered histogram, by name."""
    with _lock:
        hs = dict(_HISTOGRAMS)
    return {name: h.snapshot() for name, h in sorted(hs.items())}


def _registered_histograms() -> Dict[str, Histogram]:
    """The LIVE registered histogram objects (not snapshots) — the
    `mx.obs` sampler holds per-histogram interval states across
    ticks."""
    with _lock:
        return dict(_HISTOGRAMS)


# named callables merged into metrics() under their key — how a
# subsystem (mx.serve) surfaces its live gauges without telemetry
# importing it (the dependency points the other way)
_METRIC_PROVIDERS: Dict[str, Callable[[], Dict[str, Any]]] = {}


def register_metrics_provider(name: str,
                              fn: Callable[[], Dict[str, Any]]) -> None:
    """Merge ``fn()`` (a JSON-safe dict) into :func:`metrics` output
    under key ``name``.  A provider that raises is reported as
    ``{"error": ...}`` instead of breaking metrics()."""
    with _lock:
        _METRIC_PROVIDERS[name] = fn


def unregister_metrics_provider(name: str) -> None:
    with _lock:
        _METRIC_PROVIDERS.pop(name, None)


def _step_metrics() -> Dict[str, Any]:
    """Always-on per-step training metrics of THIS process: step
    count, latency (last/avg seconds), examples/sec over the run,
    non-finite steps skipped, device-memory watermark bytes."""
    with _lock:
        dt_sum = _METRICS["dt_sum"]
        return {
            "steps": _METRICS["steps"],
            "examples": _METRICS["examples"],
            "step_time_last_s": _METRICS["dt_last"],
            "step_time_avg_s": dt_sum / max(1, _METRICS["steps"]),
            "examples_per_sec": (_METRICS["examples"] / dt_sum)
            if dt_sum > 0 else 0.0,
            "nonfinite_steps": _METRICS["nonfinite"],
            "device_mem_watermark_bytes": _METRICS["mem_watermark"],
            "input_waits": _METRICS["input_waits"],
            "input_wait_last_s": _METRICS["input_wait_last"],
            "input_wait_avg_s": _METRICS["input_wait_sum"]
            / max(1, _METRICS["input_waits"]),
            # the attribution ratio ROADMAP item 3 wants: what share
            # of wall time went to WAITING on host input
            "input_wait_frac": (_METRICS["input_wait_sum"] / dt_sum)
            if dt_sum > 0 else 0.0,
        }


def metrics() -> Dict[str, Any]:
    """Always-on metrics of THIS process: the per-step training block
    (:func:`_step_metrics`), every registered :class:`Histogram`
    snapshot under ``"histograms"``, and each registered metrics
    provider's dict under its own key (`mx.serve` publishes its
    queue-depth / batch-occupancy / SLO gauges this way)."""
    out = _step_metrics()
    if _HISTOGRAMS:
        out["histograms"] = histograms()
    with _lock:
        providers = list(_METRIC_PROVIDERS.items())
    for name, fn in providers:
        try:
            out[name] = fn()
        except Exception as e:  # a broken provider must not take
            out[name] = {"error": str(e)}  # metrics() down with it
    return out


def snapshot(max_events: Optional[int] = None) -> Dict[str, Any]:
    """This process's full telemetry state: identity + wall-clock
    timestamp + ``profiler.stats()`` + :func:`metrics` + ring events.
    The unit that ships over the heartbeat and lands in the per-role
    ``telemetry_*.json`` files."""
    from . import profiler as _prof

    evs = events()
    if max_events is not None and len(evs) > max_events:
        evs = evs[-max_events:]
    snap = identity()
    snap["ts"] = time.time()
    snap["stats"] = _prof.stats()
    snap["metrics"] = metrics()
    snap["events"] = evs
    return snap


def hb_payload() -> Optional[Dict[str, Any]]:
    """Snapshot a role attaches to its scheduler heartbeat (capped at
    ``MXTPU_TELEMETRY_HB_EVENTS`` recent events); None when off."""
    if not _ENABLED:
        return None
    return snapshot(max_events=_HB_EVENTS)


def stat_rollup(stats) -> Dict[str, int]:
    """Derived per-node tickers from ONE ``profiler.stats()`` dict —
    the single definition shared by `mx.obs` sample rows, the live
    aggregator's per-role rows and :func:`health_rollup`, so the
    anomaly/retry/failover arithmetic cannot drift between surfaces.
    Tolerates a malformed dict (a dying role's last heartbeat)."""
    out = {"anomalies": 0, "retries": 0, "failovers": 0}
    if not isinstance(stats, dict):
        return out

    def _i(v) -> int:
        try:
            return int(v)
        except (TypeError, ValueError):
            return 0

    for k, v in stats.items():
        if k.startswith("health_anomaly::"):
            out["anomalies"] += _i(v)
        elif k.startswith("retry_attempts::"):
            out["retries"] += _i(v)
        elif k.startswith("serve_failover::"):
            out["failovers"] += _i(v)
    out["anomalies"] += _i(stats.get("health_nonfinite_steps", 0))
    out["anomalies"] += _i(stats.get("health_oom", 0))
    out["failovers"] += _i(stats.get("elastic_failover", 0))
    return out


def health_rollup(snaps: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-node snapshots into the training-health cluster view:
    per-node anomaly counts (``health_*`` counters) and the FIRST
    non-finite blame each node reported (from its ``anomaly`` events).
    Shared by ``merge_dir``'s cluster.json and the scheduler's
    ``kv.telemetry()`` view."""
    per_node: Dict[str, int] = {}
    first_nonfinite: Dict[str, Dict[str, Any]] = {}
    for key, snap in snaps.items():
        if not isinstance(snap, dict):
            continue  # a corrupt heartbeat/merge source names the
            # gap upstream; the rollup folds the survivors
        n = stat_rollup(snap.get("stats"))["anomalies"]
        if n:
            per_node[key] = n
        evs = snap.get("events")
        for ev in (evs if isinstance(evs, list) else []):
            if not isinstance(ev, dict):
                continue
            if ev.get("kind") == "anomaly" and \
                    ev.get("atype") == "nonfinite" and ev.get("layer"):
                first_nonfinite[key] = {
                    "layer": ev.get("layer"), "step": ev.get("step"),
                    "origin": ev.get("origin"), "site": ev.get("site")}
                break
    return {"anomaly_total": sum(per_node.values()),
            "per_node_anomalies": per_node,
            "first_nonfinite": first_nonfinite}


def perf_rollup(snaps: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-node snapshots into the performance cluster view:
    per-rank MFU, the worker MFU spread (straggler signal — max-min
    over ranks reporting one), and each rank's dominant phase.  Shared
    by ``merge_dir``'s cluster.json and the scheduler's
    ``kv.telemetry()`` view."""
    per_rank_mfu: Dict[str, float] = {}
    per_rank_phase: Dict[str, str] = {}
    for key, snap in snaps.items():
        if not isinstance(snap, dict):
            continue  # tolerate corrupt sources; fold the survivors
        m = snap.get("metrics")
        p = m.get("perf") if isinstance(m, dict) else None
        p = p if isinstance(p, dict) else {}
        try:
            if p.get("mfu") is not None:
                per_rank_mfu[key] = float(p["mfu"])
        except (TypeError, ValueError):
            pass
        if p.get("dominant_phase"):
            per_rank_phase[key] = str(p["dominant_phase"])
    worker_mfus = [v for k, v in per_rank_mfu.items()
                   if k.startswith("worker")] or list(per_rank_mfu.values())
    return {"per_rank_mfu": per_rank_mfu,
            "mfu_spread": (max(worker_mfus) - min(worker_mfus))
            if len(worker_mfus) >= 2 else 0.0,
            "per_rank_dominant_phase": per_rank_phase}


def hbm_rollup(snaps: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-node snapshots into the device-memory cluster view:
    per-rank used/peak/headroom bytes plus a leak flag from each role's
    ``metrics()["hbm"]`` block (the `mx.hbm` census provider).  Shared
    by ``merge_dir``'s cluster.json and the scheduler's
    ``kv.telemetry()`` view."""
    per_rank: Dict[str, Dict[str, Any]] = {}
    leak_ranks: List[str] = []
    for key, snap in snaps.items():
        if not isinstance(snap, dict):
            continue  # tolerate corrupt sources; fold the survivors
        m = snap.get("metrics")
        h = m.get("hbm") if isinstance(m, dict) else None
        if not isinstance(h, dict) or not h.get("enabled"):
            continue
        per_rank[key] = {
            "used_bytes": int(h.get("used_bytes") or 0),
            "peak_used_bytes": int(h.get("peak_used_bytes") or 0),
            "headroom_bytes": int(h.get("headroom_bytes") or 0),
            "leak": bool(h.get("leak")),
        }
        if h.get("leak"):
            leak_ranks.append(key)
        if h.get("last_leak"):
            per_rank[key]["last_leak"] = h["last_leak"]
    headrooms = [r["headroom_bytes"] for r in per_rank.values()]
    return {"per_rank": per_rank,
            "min_headroom_bytes": min(headrooms) if headrooms else None,
            "peak_used_bytes": max(
                (r["peak_used_bytes"] for r in per_rank.values()),
                default=0),
            "leak_ranks": leak_ranks}


def aggregate_stats(stat_dicts) -> Dict[str, int]:
    """Fold per-node counter snapshots into one cluster view: additive
    counters sum, :data:`GAUGE_STATS` take the max."""
    out: Dict[str, int] = {}
    for stats in stat_dicts:
        if not isinstance(stats, dict):
            continue  # a SIGKILL-truncated role may leave a non-dict
        for k, v in stats.items():  # stats block; fold the survivors
            try:
                iv = int(v)
            except (TypeError, ValueError):
                continue
            if k in GAUGE_STATS:
                out[k] = max(out.get(k, 0), iv)
            else:
                out[k] = out.get(k, 0) + iv
    return out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

_FLIGHT = {"dir": None, "signals_installed": False,
           "hooks_installed": False, "prev_handlers": {},
           "prev_excepthook": None, "prev_threadhook": None}


def _flight_dir() -> Optional[str]:
    return _FLIGHT["dir"] or getenv("MXTPU_TELEMETRY_DIR")


def _thread_stacks() -> Dict[str, List[str]]:
    """All-thread stack traces, formatted (the post-mortem hang
    answer: WHERE was every thread when the trigger fired)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        key = "%s-%d" % (names.get(tid, "thread"), tid)
        out[key] = traceback.format_stack(frame)
    return out


def _json_safe(obj):
    """Replace non-finite floats with strings so the written file is
    STRICT JSON.  Diverged runs stamp NaN/Inf grad norms into their
    step/anomaly/blame records — exactly the artifacts a post-mortem
    opens — and python's default ``json.dump`` would emit the bare
    ``NaN`` token, which chrome://tracing / ``JSON.parse`` reject
    wholesale."""
    if isinstance(obj, float):
        return obj if obj == obj and abs(obj) != float("inf") \
            else repr(obj)  # 'nan' / 'inf' / '-inf'
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _write_json(path: str, payload: Dict[str, Any]) -> Optional[str]:
    """Atomic write (temp + fsync + rename via resilience) so a crash
    mid-dump never leaves a truncated file a post-mortem tool would
    trust.  Returns None instead of raising — dump paths run inside
    signal handlers and excepthooks."""
    try:
        from .resilience import atomic_write

        with atomic_write(path, "w") as f:
            json.dump(_json_safe(payload), f, default=str,
                      allow_nan=False)
    except Exception:
        return None
    return path


def _flight_target(d: str, role: str, rank: int, pid: int) -> str:
    """Pick the path a flight dump lands at.  The base name is
    ``flight_<role><rank>.json`` — but a FRESH record there written by
    a DIFFERENT process (e.g. the posthumous corpse of the dead worker
    whose rank this survivor inherited after an elastic re-rank) must
    not be clobbered, so the dump diverts to a pid-suffixed sibling
    (still ``flight_*.json``, so the merge index picks both up).
    Records from a previous run (mtime before this process started)
    are stale and fair game."""
    base = os.path.join(d, "flight_%s%d.json" % (role, rank))
    try:
        if os.path.getmtime(base) < _START_TIME:
            return base  # leftover from an earlier run
        with open(base) as f:
            existing = json.load(f)
        if int(existing.get("pid", -1)) == pid:
            return base  # our own earlier dump: newer state wins
    except (OSError, ValueError):
        return base
    return os.path.join(d, "flight_%s%d_pid%d.json" % (role, rank, pid))


def dump_flight(reason: str, detail: str = "",
                directory: Optional[str] = None) -> Optional[str]:
    """Dump the flight record — ring events, counter snapshot, step
    metrics, all-thread stacks — as ``flight_<role><rank>.json`` in
    ``directory`` (default ``MXTPU_TELEMETRY_DIR``).  Returns the path
    or None (disabled / no directory / IO failure — never raises)."""
    d = directory or _flight_dir()
    if not _ENABLED or not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        payload = snapshot()
        payload["reason"] = str(reason)
        if detail:
            payload["detail"] = str(detail)[:2000]
        payload["threads"] = _thread_stacks()
        record("flight", trigger=str(reason))
        path = _flight_target(d, payload["role"], payload["rank"],
                              payload["pid"])
        out = _write_json(path, payload)
    except Exception:
        return None
    if out:
        from . import profiler as _prof

        _prof.inc_stat("flight_dumps")
    return out


def dump_flight_for(snap: Dict[str, Any], reason: str,
                    directory: Optional[str] = None) -> Optional[str]:
    """POSTHUMOUS flight record: the scheduler writes the dead node's
    last heartbeat-shipped snapshot on its behalf when it declares the
    node dead — a SIGKILLed rank cannot dump its own corpse, but its
    last known step/round/counters are still on record."""
    d = directory or _flight_dir()
    if not _ENABLED or not d or not isinstance(snap, dict):
        return None
    try:
        os.makedirs(d, exist_ok=True)
        payload = dict(snap)
        payload["reason"] = str(reason)
        payload["posthumous"] = True
        payload["declared_ts"] = time.time()
        role = payload.get("role", "node")
        rank = int(payload.get("rank", 0))
        pid = int(payload.get("pid", 0))
        path = os.path.join(d, "flight_%s%d.json" % (role, rank))
        try:
            if os.path.getmtime(path) >= _START_TIME:
                # a fresh record already sits at the canonical name.
                # Same pid: the node dumped its OWN richer record (e.g.
                # SIGTERM then silence) — never clobber it with this
                # staler snapshot.  Different pid: a DIFFERENT
                # incarnation died there earlier this run (elastic
                # respawn at the same rank) — divert to a pid-suffixed
                # sibling so the second death still leaves its corpse.
                with open(path) as f:
                    if int(json.load(f).get("pid", -1)) == pid:
                        return None
                path = os.path.join(
                    d, "flight_%s%d_pid%d.json" % (role, rank, pid))
        except (OSError, ValueError):
            pass  # stale leftover / unreadable: the canonical name
        return _write_json(path, payload)
    except Exception:
        return None


def _flight_signal_handler(signum, frame):
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    dump_flight("signal", name)
    try:
        # the chained previous disposition usually TERMINATES the
        # process (no atexit): let the mx.obs run ledger write its
        # final sample + summary first, so a role the launcher reaps
        # with SIGTERM still closes its trial record (idempotent; a
        # SIGKILL still leaves no summary — that asymmetry is the
        # orderly-vs-killed signal tools/check_obs.py asserts)
        from . import obs as _obs

        _obs._ledger_epilogue()
    except Exception:
        pass
    from .resilience import chain_prev_signal

    chain_prev_signal(_FLIGHT["prev_handlers"].get(signum),
                      signum, frame)


def _flight_excepthook(exc_type, exc, tb):
    dump_flight("exception", "%s: %s" % (exc_type.__name__, exc))
    prev = _FLIGHT["prev_excepthook"] or sys.__excepthook__
    prev(exc_type, exc, tb)


def _flight_threadhook(args):
    dump_flight("thread_exception", "%s: %s in %r"
                % (getattr(args.exc_type, "__name__", "?"),
                   args.exc_value, getattr(args.thread, "name", "?")))
    prev = _FLIGHT["prev_threadhook"]
    if prev is not None:
        prev(args)


def install_flight_recorder(directory: Optional[str] = None,
                            signals=(signal.SIGTERM, signal.SIGQUIT)
                            ) -> None:
    """Arm the flight recorder: set the dump directory (default
    ``MXTPU_TELEMETRY_DIR``), chain SIGTERM/SIGQUIT handlers (previous
    disposition still runs — the process dies as before, with a corpse
    on disk), and wrap sys/threading excepthooks so an unhandled
    exception dumps too.  Idempotent; signal install is skipped off
    the main thread (hooks still arm)."""
    if directory is not None:
        _FLIGHT["dir"] = os.path.abspath(directory)
    if not _FLIGHT["hooks_installed"]:
        _FLIGHT["prev_excepthook"] = sys.excepthook
        sys.excepthook = _flight_excepthook
        if hasattr(threading, "excepthook"):
            _FLIGHT["prev_threadhook"] = threading.excepthook
            threading.excepthook = _flight_threadhook
        _FLIGHT["hooks_installed"] = True
    if not _FLIGHT["signals_installed"]:
        try:
            for sig in signals:
                _FLIGHT["prev_handlers"][sig] = signal.signal(
                    sig, _flight_signal_handler)
            _FLIGHT["signals_installed"] = True
        except ValueError:
            pass  # not the main thread


def uninstall_flight_recorder() -> None:
    """Restore the previous signal handlers and excepthooks (tests)."""
    if _FLIGHT["signals_installed"]:
        for sig, prev in _FLIGHT["prev_handlers"].items():
            try:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, TypeError):
                pass
        _FLIGHT["prev_handlers"].clear()
        _FLIGHT["signals_installed"] = False
    if _FLIGHT["hooks_installed"]:
        sys.excepthook = _FLIGHT["prev_excepthook"] or sys.__excepthook__
        if hasattr(threading, "excepthook") and \
                _FLIGHT["prev_threadhook"] is not None:
            threading.excepthook = _FLIGHT["prev_threadhook"]
        _FLIGHT["hooks_installed"] = False
    _FLIGHT["dir"] = None


def flush(directory: Optional[str] = None) -> Optional[str]:
    """Write this process's final snapshot as
    ``telemetry_<role><rank>.json`` (the per-role unit
    :func:`merge_dir` consumes).  Called at exit when
    ``MXTPU_TELEMETRY_DIR`` is set; server/scheduler roles call it
    explicitly before their hard ``os._exit``."""
    d = directory or _flight_dir()
    if not _ENABLED or not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        snap = snapshot()
        path = os.path.join(d, "telemetry_%s%d.json"
                            % (snap["role"], snap["rank"]))
        return _write_json(path, snap)
    except Exception:
        return None


if getenv("MXTPU_TELEMETRY_DIR") and _ENABLED:
    # a launched role: arm the crash paths and flush a final snapshot
    # on clean interpreter exit (roles that hard-exit call flush()
    # themselves — see kvstore_server.init_module)
    import atexit

    install_flight_recorder()
    atexit.register(flush)

if hasattr(os, "register_at_fork"):
    # fork-without-exec children (DataLoader pool workers) are
    # HELPERS, not roles: they inherit the armed SIGTERM handler and
    # the parent's role/rank, so routine pool.terminate() would leave
    # crash-style flight corpses under the parent's name — and the
    # first one would claim flight_<role><rank>.json, blocking the
    # scheduler's posthumous record for the real worker.  Disarm in
    # the child; a process that execs (launch.py roles) re-imports and
    # re-arms itself.
    os.register_at_fork(after_in_child=uninstall_flight_recorder)


# ---------------------------------------------------------------------------
# Merging (per-role files -> one chrome trace + one cluster view)
# ---------------------------------------------------------------------------

def _role_key(snap: Dict[str, Any]) -> str:
    try:
        rank = int(snap.get("rank", 0))
    except (TypeError, ValueError):
        rank = 0
    return "%s%d" % (snap.get("role", "node"), rank)


def _load_snap(path: str) -> Dict[str, Any]:
    """Load one per-role JSON file STRICTLY: raises ``ValueError`` on
    torn/truncated/non-object content (a SIGKILLed role can leave any
    of those) so :func:`merge_dir` can merge the survivors and NAME
    the gap instead of crashing — or worse, silently dropping it."""
    with open(path) as f:
        snap = json.load(f)
    if not isinstance(snap, dict):
        raise ValueError("not a JSON object")
    # normalize the blocks every consumer indexes into
    if not isinstance(snap.get("stats"), dict):
        snap["stats"] = {}
    if not isinstance(snap.get("metrics"), dict):
        snap["metrics"] = {}
    evs = snap.get("events")
    snap["events"] = [e for e in evs if isinstance(e, dict)] \
        if isinstance(evs, list) else []
    return snap


def _events_to_chrome(snap: Dict[str, Any], t0: float) -> List[Dict]:
    """Telemetry ring records -> chrome trace events.  Records carry
    EPOCH timestamps, so alignment is just a shared origin ``t0``:
    ``ts_us = (ts - t0) * 1e6``.  ``step`` records with a duration
    render as complete (X) spans ending at their timestamp; everything
    else is an instant."""
    pid = int(snap.get("pid", 0))
    out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "%s (pid %d)" % (_role_key(snap), pid)}}]
    for ev in snap.get("events", []):
        ts_us = (float(ev.get("ts", t0)) - t0) * 1e6
        args = {k: v for k, v in ev.items()
                if k not in ("kind", "ts", "pid", "role", "rank")}
        dur = ev.get("dur_s")
        if ev.get("kind") == "tensor_stats":
            # per-layer grad-norm counter tracks: one ph="C" series per
            # layer, so chrome://tracing plots the norm trajectories
            # next to the step spans
            for layer, st in sorted((ev.get("stats") or {}).items()):
                out.append({"name": "grad_norm/%s" % layer,
                            "cat": "health", "ph": "C", "ts": ts_us,
                            "pid": pid, "tid": 0,
                            "args": {"grad_norm":
                                     st.get("grad_norm", 0.0)}})
            continue
        if ev.get("kind") == "perf":
            # mx.perf sampled sync points: per-program counter tracks
            # (device span + MFU when known) next to the step spans
            prog = ev.get("program", "program")
            cargs = {"device_compute_us": ev.get("device_us", 0.0),
                     "host_dispatch_us": ev.get("host_us", 0.0)}
            out.append({"name": "perf/%s" % prog, "cat": "perf",
                        "ph": "C", "ts": ts_us, "pid": pid, "tid": 0,
                        "args": cargs})
            if ev.get("mfu") is not None:
                out.append({"name": "mfu/%s" % prog, "cat": "perf",
                            "ph": "C", "ts": ts_us, "pid": pid,
                            "tid": 0, "args": {"mfu": ev["mfu"]}})
            continue
        if ev.get("kind") == "span" and dur:
            # mx.tracing causal spans: same END-timestamp convention
            # as step records; the trace id stays in args so the flow
            # events tracing.stitch emits can be matched to these
            start = max(0.0, ts_us - float(dur) * 1e6)
            out.append({"name": ev.get("name", "span"), "cat": "trace",
                        "ph": "X", "ts": start, "dur": ts_us - start,
                        "pid": pid, "tid": 0, "args": args})
            continue
        if ev.get("kind") == "step" and dur:
            # the record's ts is the step's END; when the start would
            # fall before the merged origin, clip the DURATION too so
            # the span still ends at its true instant
            start = max(0.0, ts_us - float(dur) * 1e6)
            out.append({"name": "step", "cat": "telemetry", "ph": "X",
                        "ts": start, "dur": ts_us - start,
                        "pid": pid, "tid": 0, "args": args})
        else:
            out.append({"name": ev.get("kind", "event"),
                        "cat": "telemetry", "ph": "i", "ts": ts_us,
                        "pid": pid, "tid": 0, "s": "p", "args": args})
    return out


def merge_traces(paths, out_path: Optional[str] = None) -> Dict[str, Any]:
    """Merge per-role PROFILER chrome dumps into one trace.  Each dump
    written by ``profiler.dump()`` stamps real pids into its events
    and records ``otherData.epoch_origin_s`` — the wall-clock instant
    its relative timestamps count from — so this shifts every file
    onto the earliest origin and concatenates.  Returns the merged
    trace dict (and writes it to ``out_path`` when given)."""
    loaded = []
    for p in paths:
        try:
            with open(p) as f:
                trace = json.load(f)
        except (OSError, ValueError):
            continue
        origin = trace.get("otherData", {}).get("epoch_origin_s")
        if origin is None:
            # a foreign chrome trace with no epoch anchor cannot be
            # placed on the shared axis; anchoring it at 0 would shift
            # every OTHER file by ~50 years — fall back to the file's
            # mtime as a rough anchor instead
            try:
                origin = os.path.getmtime(p)
            except OSError:
                continue
        loaded.append((float(origin), trace))
    if not loaded:
        merged = {"traceEvents": [], "displayTimeUnit": "ms"}
    else:
        t0 = min(origin for origin, _ in loaded)
        evs: List[Dict] = []
        for origin, trace in loaded:
            shift_us = (origin - t0) * 1e6
            for ev in trace.get("traceEvents", []):
                ev = dict(ev)
                if ev.get("ph") != "M" and "ts" in ev:
                    ev["ts"] = float(ev["ts"]) + shift_us
                evs.append(ev)
        merged = {"traceEvents": evs, "displayTimeUnit": "ms",
                  "otherData": {"epoch_origin_s": t0}}
    if out_path:
        _write_json(out_path, merged)
    return merged


def merge_dir(directory: str, out_trace: str = "merged_trace.json",
              out_cluster: str = "cluster.json") -> Dict[str, Any]:
    """Fold a telemetry directory — ``telemetry_<role><rank>.json``
    final snapshots, ``flight_*.json`` corpses, and any
    ``trace_*.json`` profiler dumps — into:

      * ``merged_trace.json``: ONE chrome trace with a process row per
        role-rank and all clocks aligned on the earliest epoch
        timestamp seen;
      * ``cluster.json``: the merged counter view — per-role stats +
        step metrics, the cluster aggregate (:func:`aggregate_stats`),
        per-rank average step time, the straggler spread
        (slowest/fastest worker avg step time), retry + failover
        totals, and the flight-record index.

    Returns the cluster dict."""
    snaps: Dict[str, Dict[str, Any]] = {}
    flights: List[Dict[str, Any]] = []
    # files a SIGKILLed role left truncated/torn (or that vanished
    # between listdir and open) are MERGE GAPS: the merge folds the
    # survivors and names each gap in cluster.json instead of crashing
    gaps: List[Dict[str, str]] = []
    names = sorted(os.listdir(directory))
    for name in names:
        path = os.path.join(directory, name)
        if name.startswith("telemetry_") and name.endswith(".json"):
            try:
                snap = _load_snap(path)
            except (OSError, ValueError) as e:
                gaps.append({"file": name,
                             "error": str(e) or type(e).__name__})
                continue
            snaps[_role_key(snap)] = snap
        elif name.startswith("flight_") and name.endswith(".json"):
            try:
                fl = _load_snap(path)
            except (OSError, ValueError) as e:
                gaps.append({"file": name,
                             "error": str(e) or type(e).__name__})
                continue
            flights.append({
                "file": name,
                "role": fl.get("role"), "rank": fl.get("rank"),
                "reason": fl.get("reason"),
                "posthumous": bool(fl.get("posthumous")),
                "last_step": (fl.get("metrics") or {}).get("steps"),
                "last_round": (fl.get("stats") or {}).get(
                    "kvstore_round_last"),
            })
            # a corpse's events belong on the timeline too (dead nodes
            # wrote no final telemetry_ snapshot)
            key = _role_key(fl)
            if key not in snaps:
                snaps[key] = fl

    # per-role profiler chrome dumps (trace_*.json) join the timeline
    # too; the shared origin t0 must be the EARLIEST instant any
    # source knows about — telemetry records carry epoch timestamps
    # directly, profiler dumps carry an epoch origin for their ts=0
    prof_paths = [os.path.join(directory, n) for n in names
                  if n.startswith("trace_") and n.endswith(".json")]
    prof_merged = merge_traces(prof_paths) if prof_paths else None
    all_ts = [float(ev["ts"]) for s in snaps.values()
              for ev in s.get("events", []) if "ts" in ev]
    if prof_merged and prof_merged.get("traceEvents"):
        all_ts.append(float(prof_merged["otherData"]["epoch_origin_s"]))
    t0 = min(all_ts) if all_ts else time.time()
    trace_events: List[Dict] = []
    for snap in snaps.values():
        trace_events.extend(_events_to_chrome(snap, t0))
    if prof_merged and prof_merged.get("traceEvents"):
        shift_us = (float(prof_merged["otherData"]["epoch_origin_s"])
                    - t0) * 1e6
        for ev in prof_merged["traceEvents"]:
            if ev.get("ph") != "M" and "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift_us
            trace_events.append(ev)
    # mx.tracing: stitch the span records from every snapshot into
    # chrome-trace flow events by trace id (lazy import — tracing
    # imports telemetry at module level, not the other way around)
    span_evs = [ev for s in snaps.values()
                for ev in s.get("events", [])
                if ev.get("kind") == "span"]
    tracing_rollup = None
    if span_evs:
        from . import tracing as _tracing
        flows, tracing_rollup = _tracing.stitch(span_evs, t0)
        trace_events.extend(flows)
    merged = {"traceEvents": trace_events, "displayTimeUnit": "ms",
              "otherData": {"epoch_origin_s": t0}}
    _write_json(os.path.join(directory, out_trace), merged)

    per_rank_step = {}
    for key, snap in snaps.items():
        m = snap.get("metrics") or {}
        if m.get("steps"):
            per_rank_step[key] = m.get("step_time_avg_s", 0.0)
    worker_avgs = [v for k, v in per_rank_step.items()
                   if k.startswith("worker") and v > 0]
    aggregate = aggregate_stats(s.get("stats") for s in snaps.values())
    # compile rollup (mx.inspect counters): wall-clock seconds each
    # rank spent building XLA programs, and how many of those builds
    # were RE-compiles of an already-seen program (retrace blame)
    per_rank_compile = {
        k: round((s.get("stats") or {}).get("inspect_compile_wall_us", 0)
                 / 1e6, 3)
        for k, s in snaps.items()
        if (s.get("stats") or {}).get("inspect_compile_wall_us")}
    cluster = {
        "roles": {k: {"pid": s.get("pid"), "stats": s.get("stats", {}),
                      "metrics": s.get("metrics", {})}
                  for k, s in snaps.items()},
        "aggregate": aggregate,
        "gauge_stats": list(GAUGE_STATS),
        "per_rank_step_time_s": per_rank_step,
        "straggler_spread_s": (max(worker_avgs) - min(worker_avgs))
        if worker_avgs else 0.0,
        "retry_total": sum(v for k, v in aggregate.items()
                           if k.startswith("retry_attempts::")),
        "failover_total": aggregate.get("elastic_failover", 0),
        "per_rank_compile_s": per_rank_compile,
        "compile_total": aggregate.get("inspect_compiles", 0),
        "recompile_total": aggregate.get("inspect_recompiles", 0),
        # sharding rollup (mx.shard): cluster-wide per-collective
        # payload totals from the ZeRO-1 engine, the eager collectives
        # and reshard moves (docs/sharding.md byte conventions)
        "sharding": {k: aggregate.get(k, 0)
                     for k in ("allgather_bytes", "reduce_scatter_bytes",
                               "allreduce_bytes", "alltoall_bytes",
                               "ppermute_bytes", "reshard_bytes")},
        # training-health rollup (mx.health): per-rank anomaly counts
        # and the first non-finite blame, next to the compile/step rows
        "health": health_rollup(snaps),
        # performance rollup (mx.perf): per-rank MFU + dominant phase
        # from each role's metrics()["perf"] block; the worker MFU
        # spread is the straggler signal (one slow rank drags every
        # synchronous collective down to its speed)
        "perf": perf_rollup(snaps),
        # device-memory rollup (mx.hbm): per-rank used/peak/headroom
        # and which ranks have a live leak suspect — the fleet's
        # capacity picture next to its speed picture
        "hbm": hbm_rollup(snaps),
        # causal-tracing rollup (mx.tracing): trace/span totals, how
        # many traces crossed a process boundary, and the critical
        # path of the largest stitched traces
        "tracing": tracing_rollup,
        "flights": flights,
        # files that could not be merged (truncated by a SIGKILL,
        # torn, non-JSON): the survivors above are complete, and the
        # missing contribution is NAMED instead of silently absent
        "merge_gaps": gaps,
    }
    _write_json(os.path.join(directory, out_cluster), cluster)
    return cluster


# ---------------------------------------------------------------------------
# Speedometer-style callback (gluon loops)
# ---------------------------------------------------------------------------

class Speedometer(object):
    """Per-batch callable for gluon training loops that logs the LIVE
    telemetry metrics every ``frequent`` batches — the
    `mxtpu.callback.Speedometer` idiom, but fed by the always-on
    telemetry stream instead of its own clock, so the numbers it
    prints are the same ones ``kv.telemetry()`` aggregates::

        speedo = telemetry.Speedometer(frequent=50)
        for batch in loader:
            ...; trainer.step(bs)
            speedo()
    """

    def __init__(self, frequent: int = 50, logger=None):
        import logging

        self.frequent = max(1, int(frequent))
        self.logger = logger or logging.getLogger(__name__)
        self._count = 0

    def __call__(self, *_args) -> None:
        self._count += 1
        if self._count % self.frequent:
            return
        m = metrics()
        # mx.perf columns: MFU + dominant phase from metrics()["perf"]
        # — "-" when the observatory is disabled or has no sample yet
        p = m.get("perf") or {}
        mfu = p.get("mfu")
        self.logger.info(
            "telemetry: step %d\t%.1f samples/sec\tstep %.1f ms "
            "(avg %.1f ms)\tnonfinite %d\tmem watermark %.1f MB\t"
            "MFU %s\tphase %s",
            m["steps"], m["examples_per_sec"],
            m["step_time_last_s"] * 1e3, m["step_time_avg_s"] * 1e3,
            m["nonfinite_steps"],
            m["device_mem_watermark_bytes"] / 1e6,
            ("%.3f" % mfu) if mfu is not None else "-",
            p.get("dominant_phase") or "-")
