"""Training-health observatory: NaN provenance, OOM forensics, watchdog.

`mxtpu/telemetry.py` watches the *systems* axis (step latency, counters,
flight records) and `mxtpu/inspect.py` the *compile* axis (programs,
retrace blame).  This module watches the **model** axis — the questions
an on-call engineer actually asks when a run goes sideways:

  * **Numerics provenance** — which layer produced the first NaN/Inf?
    The cheap always-on mode computes loss / global-grad-norm
    finiteness *in-graph* (one fused reduction program — never the old
    one-sync-per-array loop) and reads the scalar on a DEFERRED
    schedule so the training loop never stalls on it.  On first
    detection a one-shot **diagnostic re-execution** walks the NNVM
    graph eagerly, node by node (the same walk
    ``executor._build_graph_fn`` traces, including the AMP casts and
    the per-node RNG folding), checks every node output with
    ``isfinite`` and blames the FIRST offender: a telemetry ``anomaly``
    event, a ``health_nonfinite::<layer>`` counter, an entry in
    :func:`report`, and a flight record all name the layer.  All three
    dispatch paths participate: ``gluon.Trainer`` (CachedOp),
    ``Module.update`` (Executor) and ``FusedTrainLoop`` (whose scanned
    carry already computes per-step finiteness in-graph; it now also
    carries the global grad norm out).

  * **In-graph tensor-stat streaming** — ``MXTPU_HEALTH_STATS_EVERY=N``
    (default 0 = off) computes per-layer grad/param norms and an
    update-ratio estimate in ONE fused program every N steps, emitted
    as telemetry ``tensor_stats`` records (rendered as chrome-trace
    counter tracks by ``telemetry.merge_dir``) and summarized by
    :func:`report`.  Opt-in and retrace-free when off: the training
    programs are untouched (`tests/test_health.py` asserts the
    compiled-signature count is identical).

  * **HBM/OOM forensics** — every dispatch site runs under
    :func:`oom_scope`: an XLA ``RESOURCE_EXHAUSTED`` is re-raised as
    the typed :class:`~mxtpu.base.MemoryExhaustedError` carrying a
    forensic report — per-program peak/argument/temp bytes from the
    `mx.inspect` registry's ``memory_analysis`` (programs are named by
    layer/block, so the report attributes HBM to model parts), device
    allocator stats, and the top live buffers — and a flight record is
    dumped before the raise.

  * **Anomaly watchdog** — rolling-window detectors over the loss,
    global grad norm and step time (spike vs the window median) emit
    typed ``anomaly`` telemetry events, which ship on the scheduler
    heartbeats into the ``kv.telemetry()`` cluster view and roll up in
    ``launch.py --telemetry-dir``'s ``cluster.json``.

Cost discipline (`tools/check_health.py` asserts <10us/step): the
always-on per-step path is HOST bookkeeping only — a deque append, a
cached-median compare, and (on cadence steps) reading an
already-materialized device scalar.  The grad-health *program* runs
synchronously only when the ``MXTPU_MAX_BAD_STEPS`` guard is armed
(where it replaces N per-array syncs with one dispatch — strictly
cheaper than PR 2's loop); otherwise it is dispatched every
``MXTPU_HEALTH_CHECK_EVERY`` (16) steps and its scalar is read on the
NEXT cadence step, by which time it is long since ready (no stall).
The expensive paths — diagnostic re-execution, OOM report, stat
streaming — run only on detection or cadence.  ``MXTPU_HEALTH=0``
turns every hook into one bool check and adds ZERO records.

See `docs/observability.md` §Training health for the blame workflow,
the stat schema, and an OOM report example.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .base import MemoryExhaustedError, getenv, getenv_bool, getenv_int

__all__ = [
    "MemoryExhaustedError",
    "enabled",
    "enable",
    "check_every",
    "stats_every",
    "grad_check",
    "monitor_grads",
    "register_context",
    "on_nonfinite",
    "observe_loss",
    "observe_grad_norm",
    "observe_step",
    "maybe_stream_stats",
    "stream_stats",
    "emit_stats",
    "layer_norms",
    "want_context",
    "oom_scope",
    "is_oom",
    "memory_report",
    "report",
    "reset",
]

_ENABLED = getenv_bool("MXTPU_HEALTH", True)
_WINDOW = max(8, getenv_int("MXTPU_HEALTH_WINDOW", 64))
# spike factors vs the rolling-window median (0 disables a detector)
_LOSS_SPIKE = float(getenv("MXTPU_HEALTH_LOSS_SPIKE", "8") or 8)
_GRAD_SPIKE = float(getenv("MXTPU_HEALTH_GRAD_SPIKE", "8") or 8)
_STEP_SPIKE = float(getenv("MXTPU_HEALTH_STEP_SPIKE", "4") or 4)
# at most this many one-shot diagnostic re-executions per process (each
# walks the graph eagerly — milliseconds; a diverged run would
# otherwise re-diagnose every step of the burst)
_MAX_DIAG = max(1, getenv_int("MXTPU_HEALTH_MAX_DIAG", 4))

_lock = threading.RLock()


def enabled() -> bool:
    """Health layer on?  ``MXTPU_HEALTH=0`` opts out entirely."""
    return _ENABLED


def enable(on: bool = True) -> None:
    """Flip the health layer at runtime (tests / embedding)."""
    global _ENABLED
    _ENABLED = bool(on)


def check_every() -> int:
    """Deferred grad-finiteness cadence (``MXTPU_HEALTH_CHECK_EVERY``,
    default 16) used when the bad-step guard is not armed; 0 disables
    the deferred monitor.  Read from the environment per call (sub-us)
    so tests and embedders can retune a live process."""
    return max(0, getenv_int("MXTPU_HEALTH_CHECK_EVERY", 16))


def stats_every() -> int:
    """Per-layer tensor-stat streaming cadence
    (``MXTPU_HEALTH_STATS_EVERY``); 0 (default) = off."""
    return max(0, getenv_int("MXTPU_HEALTH_STATS_EVERY", 0))


class _Detector(object):
    """Rolling-window spike detector: value > factor * window median.
    Median is refreshed every ``_REFRESH`` appends (sorting 64 floats
    per step would be measurable; a slightly stale median is not)."""

    _REFRESH = 8
    __slots__ = ("name", "factor", "window", "_median", "_since",
                 "_last_fired", "fired")

    def __init__(self, name: str, factor: float):
        self.name = name
        self.factor = factor
        self.window: collections.deque = collections.deque(maxlen=_WINDOW)
        self._median: Optional[float] = None
        self._since = 0
        self._last_fired = -10**9
        self.fired = 0

    def observe(self, value: float, step: int) -> Optional[float]:
        """Append one observation; returns the violated median when the
        value spikes (and arms a one-window cooldown), else None."""
        spike = None
        med = self._median
        if (med is not None and self.factor > 0
                and len(self.window) >= self._REFRESH
                and value > self.factor * med and med > 0
                and step - self._last_fired >= _WINDOW // 2):
            self._last_fired = step
            self.fired += 1
            spike = med
        self.window.append(value)
        self._since += 1
        if self._since >= self._REFRESH or med is None:
            self._since = 0
            s = sorted(self.window)
            self._median = s[len(s) // 2]
        return spike


class _State(object):
    def __init__(self):
        self.loss = _Detector("loss_spike", _LOSS_SPIKE)
        self.grad = _Detector("grad_explosion", _GRAD_SPIKE)
        self.step_time = _Detector("step_time_regression", _STEP_SPIKE)
        self.nonfinite: List[Dict[str, Any]] = []   # blame records
        self.anomalies: List[Dict[str, Any]] = []   # watchdog firings
        self.last_stats: Optional[Dict[str, Any]] = None
        self.last_ctx: Optional[Tuple] = None       # diagnosis context
        self.pending = None                         # in-flight (finite, norm)
        self.pending_step = 0
        self.monitor_count = 0
        self.stats_count = 0
        self.diagnoses = 0
        self.last_bad_step = -10**9


_STATE = _State()


def reset() -> None:
    """Drop all health state (tests)."""
    global _STATE
    with _lock:
        _STATE = _State()


# ---------------------------------------------------------------------------
# In-graph grad health (finiteness + global norm in ONE program)
# ---------------------------------------------------------------------------

_GRAD_JIT = [None]


def _grad_health_fn():
    """fn(grads) -> (all_finite bool scalar, global l2 norm).  ONE
    fused XLA program over the whole gradient pytree — replaces the
    one-sync-per-array host loop the PR 2 guard used.  jax caches
    compilations per input structure, so every distinct model compiles
    this once."""
    if _GRAD_JIT[0] is None:
        import jax
        import jax.numpy as jnp

        def fn(gs):
            sq = jnp.float32(0.0)
            ok = jnp.bool_(True)
            for g in jax.tree_util.tree_leaves(gs):
                g32 = g.astype(jnp.float32)
                sq = sq + jnp.sum(jnp.square(g32))
                ok = ok & jnp.isfinite(g32).all()
            # a norm overflow is itself a non-finiteness signal: fold it
            # in so isfinite(sq) alone can't mask a per-element NaN
            return ok & jnp.isfinite(sq), jnp.sqrt(sq)

        _GRAD_JIT[0] = jax.jit(fn)
    return _GRAD_JIT[0]


def grad_check(grads) -> Tuple[bool, float]:
    """Synchronous grad health: (all finite, global l2 norm) via the
    one-program in-graph check.  Blocks on the device scalar — only
    call on guard-armed paths (the PR 2 contract; the deferred
    :func:`monitor_grads` is the no-stall variant)."""
    vals = [g for g in grads if g is not None]
    if not vals:
        return True, 0.0
    finite, norm = _grad_health_fn()(vals)
    return bool(finite), float(norm)


def monitor_grads(site: str, grads_fn: Callable[[], list]) -> None:
    """Deferred always-on grad monitoring (guard OFF): every
    ``MXTPU_HEALTH_CHECK_EVERY`` steps dispatch the in-graph health
    program and read the PREVIOUS dispatch's scalar — by then it is
    long since materialized, so the read never stalls the loop.
    Non-cadence steps cost one counter bump.  On a non-finite reading,
    :func:`on_nonfinite` runs the one-shot provenance diagnosis."""
    if not _ENABLED:
        return
    every = check_every()
    if every <= 0:
        return
    st = _STATE
    st.monitor_count += 1
    if st.monitor_count % every:
        return
    pending, pstep = st.pending, st.pending_step
    st.pending = None
    try:
        vals = [g for g in grads_fn() if g is not None]
        if vals:
            st.pending = _grad_health_fn()(vals)
            st.pending_step = _current_step()
    except Exception:
        st.pending = None
    if pending is not None:
        try:
            finite, norm = bool(pending[0]), float(pending[1])
        except Exception:
            return
        if not finite:
            on_nonfinite(site, gnorm=norm, step=pstep)
        else:
            observe_grad_norm(norm, step=pstep)


def _current_step() -> int:
    from . import telemetry as _tel

    return _tel.current_step()


# ---------------------------------------------------------------------------
# NaN provenance: diagnosis context + one-shot re-execution
# ---------------------------------------------------------------------------

def register_context(site: str, symbol, arg_names: Sequence[str],
                     aux_names: Sequence[str], arg_vals, aux_vals,
                     key, amp_dtype=None) -> None:
    """Remember the latest training dispatch so a later non-finite
    detection can re-execute it diagnostically.  Values may be raw jax
    arrays or NDArray wrappers — wrappers are unwrapped (``._data``) at
    DIAGNOSIS time, so a donated buffer (the executor's aux donation
    kills the pre-step jax arrays) resolves to the live replacement
    instead of a deleted array.  Per-step cost: two list builds."""
    if not _ENABLED:
        return
    _STATE.last_ctx = (site, symbol, arg_names, aux_names,
                       list(arg_vals), list(aux_vals), key, amp_dtype)


def want_context() -> bool:
    """Should dispatch sites still pay to capture/hold a diagnosis
    context?  False once the per-process diagnosis budget
    (``MXTPU_HEALTH_MAX_DIAG``) is spent — lets `FusedTrainLoop` drop
    its held batch stacks instead of pinning HBM for diagnoses that
    will never run."""
    return _ENABLED and _STATE.diagnoses < _MAX_DIAG


def _is_bad(v) -> bool:
    """True when an array holds a NaN/Inf (host read — diagnosis only).
    Non-float dtypes are finite by construction."""
    import jax.numpy as jnp

    try:
        if not hasattr(v, "dtype") or \
                not jnp.issubdtype(v.dtype, jnp.inexact):
            return False
        return not bool(jnp.isfinite(v).all())
    except Exception:
        return False


def _unwrap(v):
    """NDArray wrapper -> live jax array (see register_context)."""
    return getattr(v, "_data", v)


def diagnose(symbol, arg_names: Sequence[str], aux_names: Sequence[str],
             arg_vals, aux_vals, key,
             amp_dtype=None) -> Optional[Dict[str, Any]]:
    """One-shot diagnostic re-execution: walk the NNVM graph EAGERLY in
    topological order — the exact walk ``executor._build_graph_fn``
    traces, AMP casts and RNG folding included — checking every value
    with ``isfinite`` and stopping at the first offender.  Returns
    ``{"layer", "op", "origin"}`` (origin ``input`` = a graph input /
    parameter arrived non-finite; ``op`` = this node produced NaN/Inf
    from finite inputs) or None when the whole forward is finite (the
    non-finiteness arose in the backward pass only)."""
    import jax

    from . import amp as _amp
    from .passes.graph import ensure_rng_ids, rng_id_of
    from .symbol.symbol import _topo_order

    # same stable per-node RNG identity as _build_graph_fn: the
    # compiled program folds each node's __rng_id__ (pass rewrites
    # never renumber), so this eager walk must fold the SAME ids or
    # the diagnosis would draw different dropout masks than the step
    # it is explaining
    ensure_rng_ids(symbol)
    nodes = _topo_order(symbol._outputs)
    arg_pos = {n: i for i, n in enumerate(arg_names)}
    aux_pos = {n: i for i, n in enumerate(aux_names)}
    env: Dict[Tuple[int, int], Any] = {}
    rng_i = 0
    with _amp.scope(amp_dtype):
        for node in nodes:
            if node.is_variable:
                if node.is_aux:
                    val = _unwrap(aux_vals[aux_pos[node.name]])
                else:
                    val = _unwrap(arg_vals[arg_pos[node.name]])
                env[(id(node), 0)] = val
                if _is_bad(val):
                    return {"layer": node.name, "op": "variable",
                            "origin": "input"}
                continue
            invals = [env[(id(inode), idx)] for inode, idx in node.inputs]
            if amp_dtype is not None:
                invals = _amp.cast_op_inputs(node.op.name, invals,
                                             amp_dtype)
            attrs = dict(node.attrs)
            if node.op.train_aware:
                attrs["is_train"] = True
            try:
                if node.op.needs_rng:
                    sub = jax.random.fold_in(key, rng_id_of(node, rng_i))
                    rng_i += 1
                    out = node.op.fn(sub, *invals, **attrs)
                else:
                    out = node.op.fn(*invals, **attrs)
            except Exception as e:
                # the op itself failing eagerly is its own diagnosis
                return {"layer": node.name, "op": node.op.name,
                        "origin": "error:%s" % str(e)[:120]}
            if not isinstance(out, tuple):
                out = (out,)
            n_vis = node.op.n_outputs(node.attrs)
            if len(out) > n_vis and node.attrs.get("sub_aux"):
                out = out[:n_vis]
            for i, o in enumerate(out):
                env[(id(node), i)] = o
            for o in out:
                if _is_bad(o):
                    return {"layer": node.name, "op": node.op.name,
                            "origin": "op"}
    return None


def on_nonfinite(site: str, gnorm: Optional[float] = None,
                 step: Optional[int] = None,
                 ctx: Optional[Tuple] = None) -> Optional[Dict[str, Any]]:
    """A non-finite loss/grad was detected at ``site``.  Runs the
    one-shot provenance diagnosis (first detection of a burst only,
    bounded by ``MXTPU_HEALTH_MAX_DIAG``), emits the ``anomaly``
    telemetry event + ``health_nonfinite::<layer>`` counter, records
    the blame for :func:`report`, and dumps a flight record.  Returns
    the blame record (or None when disabled)."""
    if not _ENABLED:
        return None
    from . import profiler as _prof
    from . import telemetry as _tel

    if step is None:
        step = _current_step()
    st = _STATE
    with _lock:
        new_burst = step > st.last_bad_step + 1
        st.last_bad_step = max(st.last_bad_step, step)
        may_diagnose = new_burst and st.diagnoses < _MAX_DIAG
        if may_diagnose:
            st.diagnoses += 1
    _prof.inc_stat("health_nonfinite_steps")
    blame = None
    if may_diagnose:
        use = ctx if ctx is not None else st.last_ctx
        if use is not None:
            c_site, symbol, argn, auxn, argv, auxv, key, ampd = use
            try:
                t0 = time.perf_counter()
                blame = diagnose(symbol, argn, auxn, argv, auxv, key,
                                 amp_dtype=ampd)
                _prof.inc_stat("health_diagnoses")
                if blame is None:
                    # forward clean: the backward produced the
                    # non-finite values (e.g. an exploding vjp)
                    blame = {"layer": "(backward)", "op": "vjp",
                             "origin": "backward"}
                blame["site"] = site
                blame["step"] = step
                blame["diag_s"] = round(time.perf_counter() - t0, 4)
                if gnorm is not None:
                    blame["grad_norm"] = float(gnorm)
            except Exception as e:  # diagnosis is best-effort
                blame = {"layer": None, "op": None, "site": site,
                         "step": step, "origin": "diag_error",
                         "error": str(e)[:200]}
    layer = (blame or {}).get("layer")
    if layer:
        _prof.inc_stat("health_nonfinite::%s" % layer)
    rec = {"atype": "nonfinite", "site": site, "step": step}
    if gnorm is not None:
        rec["grad_norm"] = float(gnorm)
    if layer:
        rec["layer"] = layer
        rec["origin"] = blame.get("origin")
    _tel.record("anomaly", **rec)
    if blame is not None:
        with _lock:
            st.nonfinite.append(blame)
        _tel.dump_flight(
            "nonfinite", "site=%s step=%s layer=%s origin=%s"
            % (site, step, layer, blame.get("origin")))
    return blame


# ---------------------------------------------------------------------------
# Anomaly watchdog
# ---------------------------------------------------------------------------

def _fire(detector: _Detector, value: float, median: float,
          step: int, site: str) -> None:
    from . import profiler as _prof
    from . import telemetry as _tel

    _prof.inc_stat("health_anomaly::%s" % detector.name)
    rec = {"atype": detector.name, "value": round(float(value), 6),
           "median": round(float(median), 6), "step": step, "site": site}
    _tel.record("anomaly", **rec)
    with _lock:
        _STATE.anomalies.append(rec)


def observe_loss(value, step: Optional[int] = None,
                 site: str = "train") -> None:
    """Feed one loss observation to the watchdog.  NaN/Inf losses route
    to :func:`on_nonfinite`; a finite loss above
    ``MXTPU_HEALTH_LOSS_SPIKE`` x the rolling median fires a
    ``loss_spike`` anomaly."""
    if not _ENABLED:
        return
    try:
        v = float(value)
    except (TypeError, ValueError):
        return
    if step is None:
        step = _current_step()
    if v != v or v in (float("inf"), float("-inf")):
        on_nonfinite(site, step=step)
        return
    med = _STATE.loss.observe(v, step)
    if med is not None:
        _fire(_STATE.loss, v, med, step, site)


def observe_grad_norm(value: float, step: Optional[int] = None,
                      site: str = "train") -> None:
    """Feed one global-grad-norm observation (``grad_explosion``
    detector).  Called by the guard/monitor paths automatically."""
    if not _ENABLED:
        return
    if step is None:
        step = _current_step()
    med = _STATE.grad.observe(float(value), step)
    if med is not None:
        _fire(_STATE.grad, value, med, step, site)


def observe_step(step: int, dur_s: float, site: str = "train") -> None:
    """Feed one step duration (``step_time_regression`` straggler
    detector).  Wired into ``telemetry.record_step`` — the always-on
    per-step host path; keep it allocation-light."""
    if not _ENABLED or dur_s <= 0:
        return
    med = _STATE.step_time.observe(dur_s, step)
    if med is not None:
        _fire(_STATE.step_time, dur_s, med, step, site)


# ---------------------------------------------------------------------------
# In-graph tensor-stat streaming
# ---------------------------------------------------------------------------

_STATS_JIT = [None]


def _stats_fn():
    """fn(params, grads) -> (param_norms, grad_norms): per-layer l2
    norms in ONE fused program (host reads K scalars on cadence steps
    only)."""
    if _STATS_JIT[0] is None:
        import jax
        import jax.numpy as jnp

        def fn(ps, gs):
            def norm(a):
                return jnp.sqrt(jnp.sum(
                    jnp.square(a.astype(jnp.float32))))

            return [norm(p) for p in ps], [norm(g) for g in gs]

        _STATS_JIT[0] = jax.jit(fn)
    return _STATS_JIT[0]


_NORMS_JIT = [None]


def layer_norms(vals):
    """Per-array l2 norms in ONE fused program (device scalars; jax
    caches the compilation per input structure).  `FusedTrainLoop`
    pairs these param norms with the grad norms its scanned program
    already carried out."""
    if _NORMS_JIT[0] is None:
        import jax
        import jax.numpy as jnp

        def fn(vs):
            return [jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
                    for v in vs]

        _NORMS_JIT[0] = jax.jit(fn)
    return _NORMS_JIT[0](list(vals))


def maybe_stream_stats(pairs_fn: Callable[[], Tuple[List[str], list, list]],
                       scale: float = 1.0, site: str = "train") -> None:
    """Cadence gate for :func:`stream_stats`: every
    ``MXTPU_HEALTH_STATS_EVERY`` calls, build the (names, params,
    grads) triple via ``pairs_fn`` and stream the per-layer stats.
    Off-cadence cost: one counter bump."""
    n = stats_every()
    if not _ENABLED or n <= 0:
        return
    st = _STATE
    st.stats_count += 1
    if st.stats_count % n:
        return
    try:
        names, params, grads = pairs_fn()
    except Exception:
        return
    stream_stats(names, params, grads, scale=scale, site=site)


def stream_stats(names: Sequence[str], params, grads,
                 scale: float = 1.0, site: str = "train") -> None:
    """Compute per-layer param/grad norms in-graph and emit ONE
    ``tensor_stats`` telemetry record::

        {"kind": "tensor_stats", "step": N, "site": ...,
         "stats": {layer: {"param_norm", "grad_norm", "update_ratio"}}}

    ``update_ratio`` estimates |Δw|/|w| as ``scale * grad_norm /
    param_norm`` (exact for plain SGD where scale = lr * rescale_grad;
    an upper-bound proxy for adaptive optimizers).  ``merge_dir``
    renders these as chrome-trace counter tracks."""
    if not _ENABLED:
        return
    try:
        pn, gn = _stats_fn()(list(params), list(grads))
    except Exception:
        return
    emit_stats(names, pn, gn, scale=scale, site=site)


def emit_stats(names: Sequence[str], param_norms, grad_norms,
               scale: float = 1.0, site: str = "train",
               step: Optional[int] = None) -> None:
    """Emit one ``tensor_stats`` record from PRE-COMPUTED per-layer
    norms (device scalars or floats).  :func:`stream_stats` feeds this
    after its own in-graph reduction; ``FusedTrainLoop`` feeds it
    directly with norms its scanned program already carried out."""
    if not _ENABLED:
        return
    from . import profiler as _prof
    from . import telemetry as _tel

    stats: Dict[str, Dict[str, float]] = {}
    for name, p, g in zip(names, param_norms, grad_norms):
        p, g = float(p), float(g)
        stats[name] = {
            "param_norm": round(p, 6),
            "grad_norm": round(g, 6),
            "update_ratio": round(abs(scale) * g / (p + 1e-12), 8),
        }
        if g != g:  # per-layer NaN watch rides the stream for free
            _prof.inc_stat("health_nonfinite::%s" % name)
    if step is None:
        step = _current_step()
    _tel.record("tensor_stats", step=step, site=site, stats=stats)
    _prof.inc_stat("health_stats_emitted")
    with _lock:
        _STATE.last_stats = {"step": step, "site": site, "stats": stats}
    # NOT fed to the grad_explosion detector: the guard/monitor paths
    # already observe the global norm for these same steps, and a
    # second, differently-scaled sample (first-replica, post-allreduce)
    # would pollute the rolling median


# ---------------------------------------------------------------------------
# HBM/OOM forensics
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM ", "std::bad_alloc", "Unable to allocate")


def is_oom(exc: BaseException) -> bool:
    """Does this exception look like a memory exhaustion?  Covers the
    XLA/PJRT RESOURCE_EXHAUSTED strings, the C++ runtime's bad_alloc
    text, and host-side exhaustion (a Python ``MemoryError`` from a
    numpy staging buffer under RLIMIT_AS carries no marker text but IS
    the same failure)."""
    if isinstance(exc, MemoryExhaustedError):
        return False  # already typed + reported
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def memory_report(top: int = 8) -> Dict[str, Any]:
    """Forensic HBM snapshot: per-program peak bytes plus the per-class
    static memory plan (`mx.hbm.plan`) from the `mx.inspect` registry
    (programs are keyed ``site:block-name``, so the rows attribute
    memory to model parts), device allocator stats, headroom, and the
    ``top`` largest live-buffer BUCKETS from the `mx.hbm` census sweep
    — each joined to its owning (program, layer, class).  The census
    is the ONE live-array sweep in the tree; this report rides it
    rather than walking ``jax.live_arrays()`` itself, and closes with
    a static-plan-vs-live-census diff: un-planned resident bytes are
    what the compiler never asked for (caches, leaks)."""
    out: Dict[str, Any] = {"ts": time.time()}
    programs = []
    static_peak = 0
    try:
        from . import inspect as _insp

        try:
            from . import hbm as _hbm_plan
        except Exception:
            _hbm_plan = None
        for rec in _insp.programs(analyze=True):
            row = {
                "program": rec.get("name"), "site": rec.get("site"),
                "peak_bytes": rec.get("peak_bytes", 0),
                "argument_bytes": rec.get("argument_bytes", 0),
                "temp_bytes": rec.get("temp_bytes", 0),
                "output_bytes": rec.get("output_bytes", 0),
            }
            if _hbm_plan is not None and rec.get("name"):
                try:
                    mp = _hbm_plan.plan(rec["name"])
                    if "error" not in mp:
                        row["plan_classes"] = mp.get("classes")
                except Exception:
                    pass
            programs.append(row)
            static_peak = max(static_peak, int(row["peak_bytes"] or 0))
        programs.sort(key=lambda r: -(r["peak_bytes"] or 0))
    except Exception as e:
        out["registry_error"] = str(e)[:200]
    out["programs"] = programs
    try:
        from . import hbm as _hbm

        out["device_memory"] = _hbm.device_stats()
        sweep = _hbm.sweep_live(top=top)
        out["top_live_buffers"] = [
            {"shape": tuple(r["shape"]), "dtype": r["dtype"],
             "count": r["count"],
             "mbytes": round(r["bytes"] / 2**20, 3),
             "program": r["program"], "layer": r["layer"],
             "class": r["class"]}
            for r in sweep["buckets"][:top]]
        out["live_bytes_total"] = sweep["live_bytes"]
        out["used_bytes"] = _hbm.used_bytes()
        out["limit_bytes"] = _hbm.limit_bytes()
        out["headroom_bytes"] = _hbm.headroom()
        out["plan_vs_live"] = {
            "static_peak_bytes": static_peak,
            "live_bytes": sweep["live_bytes"],
            "unplanned_bytes": max(
                0, sweep["live_bytes"] - static_peak),
        }
        leak_rows = _hbm.leaks()
        if leak_rows:
            out["leaks"] = leak_rows[-4:]
    except Exception as e:
        out["device_error"] = str(e)[:200]
    return out


def _raise_memory_error(site: str, exc: BaseException) -> None:
    from . import profiler as _prof
    from . import telemetry as _tel

    _prof.inc_stat("health_oom")
    rep = memory_report()
    rep["site"] = site
    rep["xla_error"] = str(exc)[:1000]
    contributors = ", ".join(
        "%s=%.1fMB" % (p["program"], (p["peak_bytes"] or 0) / 2**20)
        for p in rep.get("programs", [])[:4]) or "none registered"
    _tel.record("anomaly", atype="oom", site=site,
                step=_current_step(),
                top_program=(rep.get("programs") or [{}])[0]
                .get("program"))
    _tel.dump_flight("oom", "site=%s top=[%s]" % (site, contributors))
    raise MemoryExhaustedError(
        "device memory exhausted at %r — per-program peak bytes "
        "(mx.inspect memory_analysis): [%s]; see .report for device "
        "stats and top live buffers.  Original: %s"
        % (site, contributors, str(exc)[:300]), report=rep) from exc


class oom_scope(object):
    """Zero-cost-on-success guard around a dispatch site: an XLA
    ``RESOURCE_EXHAUSTED`` escaping the block is re-raised as the typed
    :class:`MemoryExhaustedError` carrying :func:`memory_report`
    (flight record dumped first).  Other exceptions pass through
    untouched."""

    __slots__ = ("site",)

    def __init__(self, site: str):
        self.site = site

    def __enter__(self):
        return self

    def __exit__(self, etype, exc, tb):
        if exc is not None and _ENABLED and is_oom(exc):
            _raise_memory_error(self.site, exc)
        return False


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def report() -> Dict[str, Any]:
    """The training-health summary of this process: non-finite blame
    records (first-NaN layer provenance), watchdog anomaly firings,
    detector medians, the latest streamed tensor stats, and the
    ``health_*`` counter snapshot."""
    from . import profiler as _prof

    with _lock:
        st = _STATE
        out = {
            "enabled": _ENABLED,
            "nonfinite": list(st.nonfinite),
            "anomalies": list(st.anomalies),
            "detectors": {
                d.name: {"n": len(d.window), "median": d._median,
                         "fired": d.fired}
                for d in (st.loss, st.grad, st.step_time)},
            "tensor_stats": st.last_stats,
            "diagnoses": st.diagnoses,
        }
    out["counters"] = {k: v for k, v in _prof.stats().items()
                       if k.startswith("health_")}
    return out
