"""Python engine behind the C predict ABI (`src/predict.cc`).

The reference's predict API (`include/mxnet/c_predict_api.h:55-120`)
lets C/C++ applications embed inference: create a predictor from
symbol-json + a params blob, set inputs, forward, read outputs.  Here
the C shared library embeds CPython and drives THIS module; the compute
still runs through the same whole-graph XLA executor every Python user
gets.  Keep this module import-light: the embedded interpreter calls
`create` once per predictor.
"""
from __future__ import annotations

import io
import json
from typing import Dict, List

import numpy as np

__all__ = ["Predictor", "create"]


class Predictor(object):
    def __init__(self, symbol_json: str, param_bytes: bytes,
                 dev_type: int, dev_id: int,
                 input_shapes: Dict[str, tuple]):
        import jax

        if dev_type == 1:  # cpu requested: force before first device use
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        import mxtpu as mx
        from mxtpu.symbol.symbol import load_json

        self._mx = mx
        sym = load_json(symbol_json)
        arg_params: Dict[str, np.ndarray] = {}
        aux_params: Dict[str, np.ndarray] = {}
        if param_bytes:
            with np.load(io.BytesIO(param_bytes), allow_pickle=True) as zf:
                keys = [str(k) for k in zf["__keys__"]] \
                    if "__keys__" in zf.files else \
                    [k for k in zf.files if k != "__keys__"]
                for k in keys:
                    if k.startswith("arg:"):
                        arg_params[k[4:]] = zf[k]
                    elif k.startswith("aux:"):
                        aux_params[k[4:]] = zf[k]
                    else:
                        arg_params[k] = zf[k]

        ctx = mx.cpu(dev_id) if dev_type == 1 else mx.tpu(dev_id)
        shapes = dict(input_shapes)
        shapes.update({k: tuple(v.shape) for k, v in arg_params.items()})
        tdict = {k: v.dtype for k, v in arg_params.items()}
        # drop label-style inputs that aren't fed (grad_req null anyway)
        self._exec = sym.simple_bind(ctx=ctx, grad_req="null",
                                     type_dict=tdict, **shapes)
        for k, v in arg_params.items():
            if k in self._exec.arg_dict:
                self._exec.arg_dict[k][:] = v
        for k, v in aux_params.items():
            if k in self._exec.aux_dict:
                self._exec.aux_dict[k][:] = v
        self._input_names = list(input_shapes)
        self._outputs: List[np.ndarray] = []

    def set_input(self, key: str, flat: np.ndarray):
        dst = self._exec.arg_dict[key]
        dst[:] = np.asarray(flat, np.float32).reshape(dst.shape)

    def forward(self):
        outs = self._exec.forward(is_train=False)
        self._outputs = [np.ascontiguousarray(o.asnumpy(), np.float32)
                         for o in outs]

    def num_outputs(self) -> int:
        return len(self._exec.outputs or self._outputs)

    def output_shape(self, index: int):
        return list(self._outputs[index].shape)

    def output_data(self, index: int) -> np.ndarray:
        return self._outputs[index].reshape(-1)


def create(symbol_json: str, param_bytes: bytes, dev_type: int,
           dev_id: int, keys, indptr, shape_data) -> Predictor:
    """Entry point matching MXTPUPredCreate's flattened-shape wire
    format (reference MXPredCreate input_shape_indptr/data)."""
    shapes = {}
    for i, key in enumerate(keys):
        shapes[key] = tuple(int(s)
                            for s in shape_data[indptr[i]:indptr[i + 1]])
    return Predictor(symbol_json, param_bytes, dev_type, dev_id, shapes)
