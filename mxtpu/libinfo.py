"""Build/runtime capability report (reference `python/mxnet/libinfo.py`
+ the runtime-feature idea).  The reference enumerates compiled-in
features (CUDA, MKLDNN, OPENMP...); the TPU-native analogs are probed
live, since there is no compile-time feature matrix — JAX backends and
the optional native runtime decide what exists."""
import os

__version__ = "0.1.0"

__all__ = ["features", "find_lib_path", "__version__"]


def find_lib_path():
    """Paths of the native runtime libraries that exist (analog of the
    reference's libmxnet.so discovery)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for name in ("libmxtpu_runtime.so", "libmxtpu_predict.so",
                 "libmxtpu_c.so"):
        p = os.path.join(here, "src", "build", name)
        if os.path.exists(p):
            out.append(p)
    return out


def features():
    """Dict of capability-name -> bool, probed from the live session."""
    feats = {}
    try:
        import jax  # noqa: F401

        feats["CPU_MESH"] = True       # virtual host mesh always works
    except Exception:
        feats["CPU_MESH"] = False
    try:
        import jax

        # separate probe: backend init can fail (e.g. broken TPU
        # driver) while jax itself — and CPU meshes — work fine
        feats["TPU"] = any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        feats["TPU"] = False
    try:
        from jax.experimental import pallas  # noqa: F401

        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    libs = find_lib_path()
    feats["NATIVE_ENGINE"] = any("runtime" in p for p in libs)
    feats["C_PREDICT_ABI"] = any("predict" in p for p in libs)
    feats["C_API"] = any(p.endswith("libmxtpu_c.so") for p in libs)
    feats["BF16"] = True           # every XLA backend lowers bfloat16
    feats["INT8_QUANTIZATION"] = True
    feats["DIST_KVSTORE"] = True   # TCP PS needs no optional deps
    return feats
