"""KVStore — key-value store for gradient aggregation / parameter sync.

TPU-native re-design of the reference KVStore stack
(`include/mxnet/kvstore.h:59-439`, `src/kvstore/kvstore.cc:40-72`,
`src/kvstore/kvstore_local.h:173-275`, `src/kvstore/comm.h`,
`src/kvstore/kvstore_nccl.h`, `src/kvstore/kvstore_dist.h`).

Backends:
  * ``local`` — reduce on host-ordered device merge (the analog of
    CommCPU, `comm.h:103`): values are summed into a merge buffer via one
    fused XLA executable.
  * ``device`` / ``nccl`` — on-device merge + broadcast (the analog of
    CommDevice GPU P2P merge `comm.h:451` and the NCCL ring
    `kvstore_nccl.h:62`): device-to-device transfers ride ICI, the sum is
    one jitted executable on the merge device.
  * ``tpu`` — the north-star backend (SURVEY.md): when a
    `mxtpu.parallel` mesh is active, push/pull is an XLA all-reduce over
    the mesh's data axis (`jax.lax.psum` under shard_map); otherwise it
    degrades to the on-device merge.
  * ``dist_sync`` / ``dist_device_sync`` / ``dist_async`` — multi-process
    parameter server over TCP (`mxtpu/_ps.py`), the analog of the ps-lite
    path (`kvstore_dist.h:44`, `kvstore_dist_server.h:155`).  Roles are
    read from MXTPU_ROLE / DMLC_ROLE env (bootstrapped by
    `tools/launch.py` like the reference's dmlc-tracker).

Semantics follow the reference exactly: ``push`` reduces a list of
per-device values into a merge buffer; with an updater set the updater
mutates the stored weight, otherwise the merged value replaces the
store; ``pull`` broadcasts the stored value into the outputs
(`kvstore_local.h:173-275`).
"""
from __future__ import annotations

import pickle
import time as _time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .base import KVStoreTimeoutError, MXNetError, getenv
from .ndarray.ndarray import NDArray, zeros
from . import resilience as _res
from . import tracing as _tracing

__all__ = ["KVStore", "KVStoreTimeoutError", "create"]


def _kvstore_timeout() -> Optional[float]:
    """MXTPU_KVSTORE_TIMEOUT: seconds a dist push/pull waits for the
    server before raising KVStoreTimeoutError (default 600; <= 0 waits
    forever — the pre-resilience behavior)."""
    val = getenv("MXTPU_KVSTORE_TIMEOUT")
    t = 600.0 if val in (None, "") else float(val)
    return t if t > 0 else None


def _wire_deadline() -> float:
    """Retry budget for dist wire ops: a SINGLE attempt may legitimately
    take MXTPU_KVSTORE_TIMEOUT, so the default MXTPU_RETRY_TIMEOUT (60 s)
    would expire before the first retry ever ran — give the guarded call
    room for at least two full waits plus backoff."""
    t = _kvstore_timeout()
    return 0.0 if t is None else max(2.5 * t, 60.0)


def _key_list(key):
    return key if isinstance(key, (list, tuple)) else [key]


def _val_list(val):
    if isinstance(val, NDArray):
        return [val]
    if isinstance(val, (list, tuple)) and val and isinstance(val[0], NDArray):
        return list(val)
    raise MXNetError("invalid value type %r" % type(val))


def _group_kv(key, vals):
    """Group (possibly list-of-list) values by key, reference
    `KVStoreLocal::GroupKVPairs` (`kvstore_local.h`)."""
    keys = _key_list(key)
    if len(keys) == 1:
        return keys, [_val_list(vals)]
    if not isinstance(vals, (list, tuple)) or len(vals) != len(keys):
        raise MXNetError("one value (or list) per key required")
    return keys, [_val_list(v) for v in vals]


# ---------------------------------------------------------------------------
# Fused reduce / broadcast executables (the Comm layer).
# One jitted executable per (n, shape, dtype) signature — the analog of
# CommDevice's merge-buffer kernel (`comm.h:503-598`).
# ---------------------------------------------------------------------------

_REDUCE_CACHE: Dict[Any, Any] = {}


def _fused_sum(jax_arrays):
    import jax

    if len(jax_arrays) == 1:
        return jax_arrays[0]
    key = (len(jax_arrays), tuple(jax_arrays[0].shape),
           str(jax_arrays[0].dtype))
    fn = _REDUCE_CACHE.get(key)
    if fn is None:
        def _sum(*xs):
            acc = xs[0]
            for x in xs[1:]:
                acc = acc + x
            return acc
        fn = jax.jit(_sum)
        _REDUCE_CACHE[key] = fn
    dev = jax_arrays[0].devices() if hasattr(jax_arrays[0], "devices") else None
    target = next(iter(dev)) if dev else None
    moved = [x if target is None or
             (hasattr(x, "devices") and target in x.devices())
             else jax.device_put(x, target) for x in jax_arrays]
    return fn(*moved)


# ---------------------------------------------------------------------------
# Gradient compression — 2-bit stochastic-threshold quantization with
# error-feedback residual (reference `src/kvstore/gradient_compression.h:
# 38-134`).  quantize(g + r): +threshold where > threshold, -threshold
# where < -threshold, else 0; the residual keeps what was dropped.
# ---------------------------------------------------------------------------

class GradientCompression(object):
    def __init__(self, type="2bit", threshold=0.5):
        if type != "2bit":
            raise MXNetError("unsupported compression type %r" % type)
        if float(threshold) <= 0:
            raise MXNetError("threshold must be positive")
        self.type = type
        self.threshold = float(threshold)
        self._residuals: Dict[Any, Any] = {}
        self._fn = None

    def _compiled(self):
        import jax
        import jax.numpy as jnp

        if self._fn is None:
            t = self.threshold

            def quant(g, r):
                x = g + r
                q = jnp.where(x > t, t, jnp.where(x < -t, -t, 0.0)
                              ).astype(g.dtype)
                return q, x - q
            self._fn = jax.jit(quant)
        return self._fn

    def compress(self, key, grad_jax):
        r = self._residuals.get(key)
        if r is None:
            import jax.numpy as jnp

            r = jnp.zeros(grad_jax.shape, grad_jax.dtype)
        q, r_new = self._compiled()(grad_jax, r)
        self._residuals[key] = r_new
        return q


# ---------------------------------------------------------------------------
# Base / local / device KVStore
# ---------------------------------------------------------------------------

class KVStore(object):
    """In-process KVStore (`local`); see module docstring."""

    def __init__(self):
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None
        self._compression: Optional[GradientCompression] = None

    @property
    def type(self):
        return "local"

    # -- init ---------------------------------------------------------------
    def init(self, key, value):
        keys, values = _group_kv(key, value)
        for k, vals in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            if len(vals) != 1:
                raise MXNetError("init requires a single value per key")
            self._store[k] = vals[0].copy()

    # -- push/pull ----------------------------------------------------------
    def _reduce(self, k, vals: List[NDArray]) -> NDArray:
        raws = [v._data for v in vals]
        merged = _fused_sum(raws)
        if self._compression is not None:
            merged = self._compression.compress(k, merged)
        return NDArray(merged, ctx=vals[0].ctx, _committed=True)

    def push(self, key, value, priority=0):
        from .ndarray.sparse import (BaseSparseNDArray, RowSparseNDArray,
                                     add as _sp_add)

        keys, values = _group_kv(key, value)
        for k, vals in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            stored = self._store[k]
            if any(isinstance(v, BaseSparseNDArray) for v in vals):
                # row-sparse merge (reference KVStoreLocal sparse push):
                # rsp grads sum sparsely, and the updater sees the
                # MERGED sparse grad so lazy row updates stay lazy
                if not all(isinstance(v, RowSparseNDArray) for v in vals):
                    raise MXNetError(
                        "push of mixed sparse/dense values for key %r "
                        "is not supported" % (k,))
                merged = vals[0]
                for v in vals[1:]:
                    merged = _sp_add(merged, v)
                if self._updater is not None:
                    self._updater(k, merged, stored)
                else:
                    stored._set_jax(merged.todense()._data)
                continue
            # resilience chokepoint sits BEFORE the updater mutates the
            # stored weight, so a retried push never double-applies
            merged = _res.guarded("kvstore_push", self._reduce, k, vals)
            if self._updater is not None:
                self._updater(k, merged, stored)
            else:
                stored._set_jax(merged._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _group_kv(key, out)
        for k, dsts in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            src = self._store[k]
            for d in dsts:
                if d.stype != "default":
                    raise MXNetError(
                        "pull into %s output: use row_sparse_pull"
                        % d.stype)
                # pull is idempotent: the whole copy is retry-safe
                _res.guarded("kvstore_pull", src.copyto, d)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority=priority)
        self.pull(key, out=out if out is not None else value,
                  priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in `row_ids` (reference
        `KVStoreLocal::PullRowSparseImpl`).  Dense store: gathers rows."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _group_kv(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs[0]) > 1:
            rids = rids * len(outs[0])
        from .ndarray import sparse as _sp

        for k, dsts in zip(keys, outs):
            src = self._store[k]
            for d, rid in zip(dsts, rids):
                _sp.retain_rows_into(src, rid, d)

    # -- updater / optimizer ------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    def _set_updater(self, updater):
        self.set_updater(updater)

    def set_optimizer(self, optimizer):
        from . import optimizer as opt_mod

        self._optimizer = optimizer
        self.set_updater(opt_mod.get_updater(optimizer))

    def set_gradient_compression(self, compression_params):
        params = dict(compression_params or {})
        self._compression = GradientCompression(
            type=params.get("type", "2bit"),
            threshold=params.get("threshold", 0.5))

    # -- distributed surface (degenerate single-process defaults) -----------
    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @property
    def live_workers(self):
        """Workers currently alive in the group (elastic membership —
        see `docs/elastic.md`).  Equals :attr:`num_workers` for
        non-distributed stores."""
        return self.num_workers

    def barrier(self):
        pass

    def telemetry(self):
        """Merged telemetry view (`docs/observability.md`).  For
        non-distributed stores this is just the local process:
        ``{"nodes": {<id>: snapshot}, "aggregate": stats}``.
        `KVStoreDist` overrides with the scheduler's cluster view
        built from heartbeat-shipped per-node snapshots."""
        from . import telemetry as _tel

        snap = _tel.snapshot()
        return {"nodes": {"local": snap},
                "aggregate": dict(snap["stats"])}

    def send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Persist the active updater's state buffers (momentum/Adam
        moments, update counters) — reference `python/mxnet/kvstore.py`
        saves `self._updater.get_states()`, not the optimizer object."""
        if self._updater is None:
            raise MXNetError(
                "load/save optimizer states is only supported when an "
                "updater is set (update_on_kvstore)")
        with _res.atomic_write(fname) as f:
            f.write(self._updater.get_states(dump_optimizer=dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError(
                "load/save optimizer states is only supported when an "
                "updater is set (update_on_kvstore)")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def close(self):
        pass


class KVStoreDevice(KVStore):
    """On-device merge + broadcast (CommDevice / NCCL analog): identical
    host logic, but the merge is pinned to the first value's device so
    transfers ride device interconnect, never the host."""

    @property
    def type(self):
        return "device"


class KVStoreTPU(KVStoreDevice):
    """`tpu` backend: XLA all-reduce over the active mesh's data axis.

    With a live mesh whose data axis matches the number of pushed
    per-device values, the merge is `jax.lax.psum` under shard_map (one
    compiled collective over ICI); otherwise falls back to the on-device
    fused merge.  This is the BASELINE.json ``kvstore=tpu`` north star.

    Mesh and axis resolve through the sharding backbone: an explicit
    ctor arg wins, then the `MeshContext` stack, then the active
    `mx.shard.ShardingPlan` (mesh AND data-axis name) — the collective
    is chosen from the plan, not hand-wired per call site.
    """

    def __init__(self, mesh=None, axis=None):
        super().__init__()
        self._mesh = mesh
        self._axis = axis  # None = the active plan's data axis
        self.last_reduce_path = None  # "psum" | "fallback" (introspection)
        self._warned_fallback = False

    @property
    def type(self):
        return "tpu"

    def _resolve(self):
        """(mesh, axis) for this reduce, via the backbone order."""
        from .parallel.mesh import current_mesh
        from .sharding.plan import current_plan

        plan = current_plan()
        axis = self._axis or (plan.data_axis if plan is not None
                              else "dp")
        mesh = self._mesh or current_mesh() or \
            (plan.mesh if plan is not None else None)
        return mesh, axis

    def _dp_line_mesh(self, mesh, n, axis):
        """A 1-D sub-mesh over the `n` devices forming the reduce axis.
        For a 1-D (or effectively-1-D) mesh that is the mesh itself; for
        a multi-axis mesh (dp, tp, ...) it is the dp line at index 0 of
        every other axis — the n Module replicas map onto it in order."""
        if axis not in mesh.shape or mesh.shape[axis] != n:
            return None
        if len(mesh.devices.flat) == n:
            if len(mesh.axis_names) == 1:
                return mesh
            from jax.sharding import Mesh

            return Mesh(mesh.devices.reshape(n), (axis,))
        from jax.sharding import Mesh

        ai = list(mesh.axis_names).index(axis)
        line = np.moveaxis(mesh.devices, ai, 0).reshape(n, -1)[:, 0]
        return Mesh(line, (axis,))

    def _reduce(self, k, vals: List[NDArray]) -> NDArray:
        mesh, axis = self._resolve()
        n = len(vals)
        line = self._dp_line_mesh(mesh, n, axis) \
            if mesh is not None and n > 1 else None
        if line is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            from .parallel import collectives

            # one shard per pushed value, placed on the reduce-line
            # devices in order — no host round-trip, replica i's gradient
            # stays on (or moves device-to-device to) line device i
            sharding = NamedSharding(line, PartitionSpec(axis))
            shape0 = vals[0].shape
            line_devs = list(line.devices.flat)
            shards = [jax.device_put(v._data.reshape((1,) + shape0), d)
                      for v, d in zip(vals, line_devs)]
            stacked = jax.make_array_from_single_device_arrays(
                (n,) + shape0, sharding, shards)
            merged = collectives.all_reduce(stacked, axis=axis,
                                            mesh=line)[0]
            if self._compression is not None:
                merged = self._compression.compress(k, merged)
            self.last_reduce_path = "psum"
            return NDArray(merged, ctx=vals[0].ctx, _committed=True)
        if mesh is not None and n > 1 and not self._warned_fallback:
            import logging

            logging.getLogger(__name__).warning(
                "kvstore=tpu: %d pushed values do not line up with the "
                "mesh's %r axis (shape %s) — falling back to the fused "
                "device merge (no XLA collective)", n, axis,
                dict(mesh.shape))
            self._warned_fallback = True
        self.last_reduce_path = "fallback"
        return super()._reduce(k, vals)


# ---------------------------------------------------------------------------
# Distributed KVStore (parameter server over TCP — `mxtpu/_ps.py`)
# ---------------------------------------------------------------------------

class KVStoreDist(KVStoreDevice):
    """Multi-process KVStore: local device merge, then push/pull against
    the server group (reference `KVStoreDist`, `kvstore_dist.h:44`).

    sync mode: the server accumulates pushes from all workers, then
    applies its updater once (`kvstore_dist_server.h:346-358`); async:
    the server applies each push immediately.
    """

    def __init__(self, type_name="dist_sync"):
        super().__init__()
        self._type = type_name
        from . import _ps

        self._worker = _ps.Worker.from_env()

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return self._worker.rank

    @property
    def num_workers(self):
        """CONFIGURED group size (nw0).  Deliberately static under
        elastic membership: gradient averaging stays scaled by nw0
        (Module/Trainer rescale_grad) while the server rescales short
        rounds by ``nw0/live`` — so `dist_sync` means "average over the
        live workers" at every group size.  See :attr:`live_workers`."""
        return self._worker.num_workers

    @property
    def live_workers(self):
        """Workers currently alive per the scheduler's dead-node
        detector (elastic membership, `docs/elastic.md`)."""
        try:
            return int(self._worker.group_info().get(
                "num_workers", self._worker.live_workers))
        except (ConnectionError, OSError):
            return self._worker.live_workers

    @property
    def rejoined(self):
        """True when this worker re-registered into a group that was
        already running (a respawned/late-joining elastic worker): it
        must pull current weights and resume at
        :meth:`current_version` instead of training from step 0."""
        return self._worker.rejoined

    def current_version(self, key):
        """Applied sync-round count of ``key`` on its servers — the
        group's current training step for elastic resume."""
        return self._worker.key_version(key)

    def init(self, key, value):
        keys, values = _group_kv(key, value)
        rejoined = self._worker.rejoined
        for k, vals in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            self._store[k] = vals[0].copy()
            if self._worker.rank == 0 and not rejoined:
                self._worker.init(k, vals[0].asnumpy())
            else:
                # non-root ranks AND rejoining workers must not reset
                # server state — the weights (and their round versions)
                # already live there
                self._worker.register_meta(k, vals[0].shape,
                                           vals[0].dtype)
        if not rejoined:
            # a rejoiner must not barrier: the running group is not at
            # a rendezvous point
            self._worker.barrier()

    def push(self, key, value, priority=0):
        from .ndarray.sparse import RowSparseNDArray, add as _sp_add

        keys, values = _group_kv(key, value)
        sync = self._type != "dist_async"
        for k, vals in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            if all(isinstance(v, RowSparseNDArray) for v in vals):
                # rows-only on the wire (reference kRowSparsePushPull)
                merged = vals[0]
                for v in vals[1:]:
                    merged = _sp_add(merged, v)
                rows = np.asarray(merged.indices.asnumpy(), np.int64)
                data = np.asarray(merged.data.asnumpy())
                valid = rows < merged.shape[0]  # drop OOB grad padding
                _res.guarded("kvstore_push", self._worker.push_rows, k,
                             rows[valid], data[valid], sync=sync,
                             timeout=_kvstore_timeout(),
                             _retry_deadline=_wire_deadline())
                continue
            merged = self._reduce(k, vals)
            # AT-LEAST-ONCE on retry: a reply lost after the server
            # applied the push means the resend double-applies (the
            # server dedups nothing yet — multi-host idempotency is
            # future work).  Injected faults fire before the send, so
            # injection replay stays exact.
            # mx.tracing: the wire round is one child span of the
            # ambient step trace; the CHILD context goes ambient so
            # the PS worker stamps ITS span id on the wire and the
            # server-side spans parent under this segment
            trc = _tracing.current()
            if trc is None:
                _res.guarded("kvstore_push", self._worker.push, k,
                             merged.asnumpy(), sync=sync,
                             timeout=_kvstore_timeout(),
                             _retry_deadline=_wire_deadline())
            else:
                kctx = trc.child()
                t0 = _time.perf_counter()
                try:
                    with _tracing.use(kctx):
                        _res.guarded("kvstore_push", self._worker.push,
                                     k, merged.asnumpy(), sync=sync,
                                     timeout=_kvstore_timeout(),
                                     _retry_deadline=_wire_deadline())
                finally:
                    _tracing.record_span(kctx, "kvstore_push",
                                         _time.perf_counter() - t0,
                                         root=True, key=str(k))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _group_kv(key, out)
        for k, dsts in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            trc = _tracing.current()
            if trc is None:
                arr = _res.guarded("kvstore_pull", self._worker.pull,
                                   k, sync=self._type != "dist_async",
                                   timeout=_kvstore_timeout(),
                                   _retry_deadline=_wire_deadline())
            else:
                kctx = trc.child()
                t0 = _time.perf_counter()
                try:
                    with _tracing.use(kctx):
                        arr = _res.guarded(
                            "kvstore_pull", self._worker.pull, k,
                            sync=self._type != "dist_async",
                            timeout=_kvstore_timeout(),
                            _retry_deadline=_wire_deadline())
                finally:
                    _tracing.record_span(kctx, "kvstore_pull",
                                         _time.perf_counter() - t0,
                                         root=True, key=str(k))
            src = NDArray(np.asarray(arr), ctx=dsts[0].ctx)
            for d in dsts:
                if d.stype != "default":
                    raise MXNetError(
                        "pull into %s output: use row_sparse_pull"
                        % d.stype)
                src.copyto(d)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull ONLY the requested rows over the wire (reference
        `src/kvstore/kvstore_dist.h` PullRowSparse): the worker asks each
        server for the flat spans its chunk holds of those rows —
        traffic is O(rows * width), never the full value."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _group_kv(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs[0]) > 1:
            rids = rids * len(outs[0])
        from .ndarray import sparse as _sp

        sync = self._type != "dist_async"
        for k, dsts in zip(keys, outs):
            for d, rid in zip(dsts, rids):
                rid_np = np.asarray(
                    rid.asnumpy() if isinstance(rid, NDArray) else rid
                ).reshape(-1)
                rows, data = _res.guarded(
                    "kvstore_pull", self._worker.pull_rows, k, rid_np,
                    sync=sync, timeout=_kvstore_timeout(),
                    _retry_deadline=_wire_deadline())
                _sp.set_rows_into(rows, data, d)

    def set_optimizer(self, optimizer):
        # reference: optimizer is serialized to the servers and runs there
        # (`python/mxnet/kvstore.py set_optimizer` → SendCommandToServers)
        self._optimizer = optimizer
        if self._worker.rejoined:
            return  # servers already run the updater; group isn't at a
            # rendezvous point, so neither command nor barrier
        if self._worker.rank == 0:
            self._worker.send_command("set_optimizer",
                                      pickle.dumps(optimizer))
        self._worker.barrier()

    def barrier(self):
        self._worker.barrier()

    def telemetry(self):
        """The scheduler's merged cluster view: per-node telemetry
        snapshots (shipped on the heartbeat channel) plus aggregated
        counter totals (`docs/observability.md`)."""
        return self._worker.telemetry()

    def send_command_to_servers(self, head, body):
        self._worker.send_command(head, body)

    # -- fleet checkpointing (mxtpu/checkpoint.py) ------------------------
    def checkpoint_stamp(self, rnd):
        """The scheduler's idempotent (round, generation,
        live-worker-set) fleet checkpoint stamp for round ``rnd`` —
        every worker asking at the same boundary gets the SAME id
        (docs/checkpoint.md)."""
        return self._worker.checkpoint_stamp(int(rnd))

    def server_checkpoint(self, directory, stamp):
        """Command every live server to snapshot its shard (store +
        version vector + updater state) into ``directory`` for the
        stamped round.  Servers capture under their lock and write on
        a background thread; rank 0's fleet-manifest commit polls for
        the resulting per-server manifests."""
        self._worker.send_command(
            "mxtpu_ckpt", {"dir": str(directory),
                           "id": stamp.get("id"),
                           "round": int(stamp["round"]),
                           "gen": int(stamp.get("gen", 0))})

    def resume_at_version(self, version):
        """Anchor push/pull round numbering at a restored checkpoint
        round R: the first post-resume push lands as round R+1 against
        the servers' restored version vectors, and sync pulls require
        ``>= R`` (see `_ps.Worker.resume_at_version`)."""
        self._worker.resume_at_version(int(version))

    def num_dead_node(self, node_id=6, timeout=None):
        """Count nodes with no heartbeat within `timeout` seconds
        (default ``MXTPU_DEAD_TIMEOUT``; reference
        `include/mxnet/kvstore.h:346-355` get_num_dead_node).
        `node_id` is the ps-lite group mask: 2 servers | 4 workers
        (default: both).  Nodes the scheduler has DECLARED dead (and
        re-ranked around) are always counted.  Scheduler liveness is
        not tracked — a dead scheduler surfaces as a ConnectionError
        from this very query."""
        count = 0
        for nid in self._worker.num_dead_nodes(timeout):
            group = 2 if nid % 2 == 0 else 4  # servers 8+2r, workers 9+2r
            if node_id & group:
                count += 1
        return count

    def close(self):
        self._worker.close()


# ---------------------------------------------------------------------------
# Factory (reference `src/kvstore/kvstore.cc:40-72`)
# ---------------------------------------------------------------------------

def create(name: str = "local", **kwargs) -> KVStore:
    name = (name or "local").lower()
    if name.startswith("dist"):
        return KVStoreDist(name)
    if name == "tpu":
        return KVStoreTPU(**kwargs)
    if name in ("device", "nccl"):
        return KVStoreDevice()
    if name == "local":
        return KVStore()
    raise MXNetError("unknown kvstore type %r" % name)
