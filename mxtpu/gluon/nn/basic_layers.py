"""Basic neural network layers (reference: `python/mxnet/gluon/nn/
basic_layers.py`): Sequential, Dense, Dropout, BatchNorm, LayerNorm,
InstanceNorm, Embedding, Flatten, Lambda, HybridLambda, activations.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "LayerNorm", "InstanceNorm", "Embedding", "Flatten", "Lambda",
           "HybridLambda", "Activation", "LeakyReLU", "PReLU", "ELU", "SELU",
           "GELU", "Swish"]


class Sequential(Block):
    """Stack of blocks run sequentially (reference `basic_layers.py:29`)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference `basic_layers.py:142`)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._units = units
            self._flatten = flatten
            self._use_bias = use_bias
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F._copy(x)


class BatchNorm(HybridBlock):
    """Batch normalization (reference `basic_layers.py:273`).  Running
    stats are aux parameters updated by the op/graph in train mode."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._kwargs = {"axis": axis, "eps": epsilon,
                            "momentum": momentum, "fix_gamma": not scale,
                            "use_global_stats": use_global_stats}
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd as _ag
        from ... import ndarray as _nd

        if F is _nd:
            # eager path: run with full outputs and update running stats
            out, mean, var = _nd.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                _full_outputs=True, **self._kwargs)
            if _ag.is_training() and not self._kwargs["use_global_stats"]:
                m = self._kwargs["momentum"]
                running_mean._set_jax(
                    (m * running_mean + (1 - m) * mean)._data)
                running_var._set_jax(
                    (m * running_var + (1 - m) * var)._data)
            return out
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def cast(self, dtype):
        if np.dtype(dtype) == np.float16:
            dtype = "float32"  # BN stats stay fp32 (reference behavior)
        super().cast(dtype)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._axis = axis
            self._epsilon = epsilon
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._epsilon = epsilon
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Lookup table.  ``sparse_grad=True`` keeps the weight gradient
    row-sparse end to end (tape emits a SparseCot, the grad buffer is a
    RowSparseNDArray, and optimizers apply lazy row updates) — the
    reference's EmbeddingOpBackwardEx path, re-designed with static
    shapes (`mxtpu/autograd.py:_record_embedding_sparse`)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                            "dtype": dtype, "sparse_grad": sparse_grad}
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse" if sparse_grad else "default",
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        from ... import ndarray as _nd

        if isinstance(function, str):
            if not hasattr(_nd, function):
                raise MXNetError("function %r not found in nd" % function)
            self._func_impl = getattr(_nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._function = function

    def hybrid_forward(self, F, *args):
        if isinstance(self._function, str):
            return getattr(F, self._function)(*args)
        return self._function(F, *args)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as _init_mod

        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or _init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
