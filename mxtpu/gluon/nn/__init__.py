"""`mxtpu.gluon.nn` (reference: `python/mxnet/gluon/nn/`)."""
from .basic_layers import *
from .conv_layers import *
from .basic_layers import Sequential, HybridSequential, Dense, Dropout, \
    BatchNorm, LayerNorm, InstanceNorm, Embedding, Flatten, Lambda, \
    HybridLambda, Activation, LeakyReLU, PReLU, ELU, SELU, GELU, Swish
from ..block import Block, HybridBlock, SymbolBlock
