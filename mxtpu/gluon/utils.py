"""Gluon utilities (reference: `python/mxnet/gluon/utils.py`):
split_data / split_and_load / clip_global_norm."""
from __future__ import annotations

import math
from typing import List

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data: NDArray, num_slice: int, batch_axis=0,
               even_split=True) -> List[NDArray]:
    """Split along the batch axis into `num_slice` pieces (reference
    `utils.py:31`)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            "data with shape %s cannot be evenly split into %d slices; "
            "set even_split=False" % (data.shape, num_slice))
    step = size // num_slice
    if not even_split:
        step = int(math.ceil(size / num_slice))
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = min(size, (i + 1) * step)
        if begin >= end:
            break
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and move each slice to one context (reference `utils.py:88`)."""
    if not isinstance(data, NDArray):
        data = nd_array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: List[NDArray], max_norm: float,
                     check_isfinite=True):
    """Rescale so the joint L2 norm <= max_norm (reference
    `utils.py:117`)."""

    def _norm(a):
        return float((a * a).sum().asnumpy())

    total = math.sqrt(sum(_norm(a) for a in arrays))
    if check_isfinite and not math.isfinite(total):
        import warnings

        warnings.warn("nan or inf found in gradients; clip_global_norm did "
                      "not rescale")
        return total
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._set_jax((arr * scale)._data)
    return total


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):  # pragma: no cover
    raise MXNetError(
        "download() is unavailable: this environment has no network egress. "
        "Place files locally and pass the path instead.")
