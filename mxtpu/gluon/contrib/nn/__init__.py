"""Experimental layers (reference gluon/contrib/nn)."""
from . import basic_layers  # noqa: F401
from .basic_layers import (Concurrent, HybridConcurrent, Identity,  # noqa: F401
                           PixelShuffle2D, SparseEmbedding,
                           SyncBatchNorm)
