"""Experimental gluon layers (reference
`python/mxnet/gluon/contrib/nn/basic_layers.py`)."""
from __future__ import annotations

from ... import nn
from ...block import Block, HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity",
           "SparseEmbedding", "SyncBatchNorm", "PixelShuffle2D"]


class Concurrent(nn.Sequential):
    """Run children on the SAME input and concat their outputs along
    `axis` (reference Concurrent — the Inception-branch container)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd

        return nd.concat(*[block(x) for block in self._children.values()],
                         dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent (reference HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        return F.concat(*[block(x) for block in self._children.values()],
                        dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block (reference Identity): the no-op branch of a
    Concurrent."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose weight gradient is row-sparse (reference
    contrib.nn.SparseEmbedding over `_contrib_SparseEmbedding`): a step
    touches only the rows present in the batch — the point of sparse
    tables at large vocab."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse")

    def forward(self, x):
        from .... import ndarray as nd

        return nd.contrib.SparseEmbedding(x, self.weight.data(),
                                          **self._kwargs)

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim}, {dtype})" \
            .format(**self._kwargs)


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device synchronized BatchNorm (reference
    contrib.nn.SyncBatchNorm).

    TPU-native mechanics: under the SPMD executor the batch axis is
    SHARDED, and XLA turns the batch-mean/var reductions into global
    collectives automatically — plain BatchNorm *is* synchronized
    BatchNorm in a pjit program, so this class shares its parent's
    compute path (no per-device statistics exist to diverge).  The
    `num_devices` argument is accepted for API parity and ignored;
    manual `shard_map` programs with explicit axis names should psum
    their own statistics (see `mxtpu.parallel`)."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class PixelShuffle2D(HybridBlock):
    """Sub-pixel upsampling: (N, C*r^2, H, W) -> (N, C, H*r, W*r)
    (reference contrib PixelShuffle2D; ESPCN superresolution)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = (factor, factor) if isinstance(factor, int) \
            else tuple(factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factor
        x = F.Reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))
        x = F.Reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        # merge via two reshapes: interleave factor dims with spatial
        x = F.Reshape(x, shape=(0, 0, -3, -3))
        return x

    def __repr__(self):
        return "PixelShuffle2D(factor=%s)" % (self._factor,)
