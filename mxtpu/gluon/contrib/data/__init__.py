"""Experimental data utilities (reference gluon/contrib/data)."""
from . import sampler  # noqa: F401
from .sampler import IntervalSampler  # noqa: F401
