"""Experimental samplers (reference
`python/mxnet/gluon/contrib/data/sampler.py`)."""
from __future__ import annotations

from ...data.sampler import Sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(Sampler):
    """Sample at fixed intervals, rolling the start offset (reference
    IntervalSampler): for length 6, interval 2 yields 0,2,4,1,3,5."""

    def __init__(self, length, interval, rollover=True):
        if interval > length:  # interval == length is legal (reference)
            raise ValueError("interval (%d) must be <= length (%d)"
                             % (interval, length))
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        starts = range(self._interval) if self._rollover else [0]
        for start in starts:
            for i in range(start, self._length, self._interval):
                yield i

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
