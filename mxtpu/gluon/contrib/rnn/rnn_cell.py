"""Experimental recurrent cells (reference
`python/mxnet/gluon/contrib/rnn/rnn_cell.py`)."""
from __future__ import annotations

import numpy as np

from ...rnn.rnn_cell import HybridRecurrentCell, ModifierCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (locked) dropout (Gal & Ghahramani 2016): ONE
    dropout mask per sequence, shared across time steps, separately for
    inputs/states/outputs.  Masks are drawn on the first step after
    `reset()` (reference VariationalDropoutCell)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._masks = {}

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._masks = {}

    def _mask(self, name, rate, like):
        from .... import autograd, random

        if not rate or not autograd.is_training():
            return None
        if name not in self._masks:
            keep = 1.0 - rate
            bern = random.uniform(0, 1, like.shape, ctx=like.ctx) < keep
            self._masks[name] = bern.astype(like.dtype) / keep
        return self._masks[name]

    def hybrid_forward(self, F, inputs, states):
        # mask draws happen Python-side once per sequence (same shape
        # every step), like ZoneoutCell's state bookkeeping; the normal
        # Block __call__ path (hooks, counters) stays intact
        m = self._mask("inputs", self.drop_inputs, inputs)
        if m is not None:
            inputs = inputs * m
        if self.drop_states and states:
            sm = self._mask("states", self.drop_states, states[0])
            if sm is not None:
                states = [states[0] * sm] + list(states[1:])
        output, next_states = self.base_cell(inputs, states)
        om = self._mask("outputs", self.drop_outputs, output)
        if om is not None:
            output = output * om
        return output, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()  # fresh masks per sequence (the cell's contract)
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a hidden-state projection (LSTMP, Sak et al. 2014;
    reference contrib.rnn.LSTMPCell): the recurrent state is projected
    to `projection_size` < hidden_size, shrinking the h2h matmul — the
    trick that made large-vocab speech LSTMs tractable."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        prev_r, prev_c = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(prev_r, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        in_g, forget_g, cell_g, out_g = F.SliceChannel(
            gates, num_outputs=4, axis=1)
        i = F.sigmoid(in_g)
        f = F.sigmoid(forget_g)
        c_tilde = F.Activation(cell_g, act_type="tanh")
        o = F.sigmoid(out_g)
        next_c = f * prev_c + i * c_tilde
        hidden = o * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
