"""Convolutional recurrent cells (reference
`python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py`).

Gates are computed by a convolution over the input plus a convolution
over the hidden state (h2h kernels must be odd so SAME padding keeps
the spatial shape).  NCHW-family layouts only (`NCW`/`NCHW`/`NCDHW`) —
the TPU build runs conv internals channels-last regardless via
MXTPU_CONV_LAYOUT, so the API layout adds nothing here (documented
scope cut vs the reference's conv_layout parameter).
"""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvCellBase(HybridRecurrentCell):
    """Shared machinery: parameter shapes, SAME h2h padding, the two
    gate convolutions."""

    _num_gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dims=2, activation="tanh", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._dims = dims
        self._input_shape = tuple(input_shape)   # (C, *spatial)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise ValueError("h2h_kernel must be odd (SAME padding "
                             "keeps the state shape); got %s"
                             % (self._h2h_kernel,))
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_dilate = _tup(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        in_c = self._input_shape[0]
        spatial = self._input_shape[1:]
        self._state_spatial = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad,
                                  self._i2h_dilate, self._i2h_kernel))
        total = hidden_channels * self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(total, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(total, hidden_channels) + self._h2h_kernel,
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(total,), init=i2h_bias_initializer,
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(total,), init=h2h_bias_initializer,
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hidden_channels) + self._state_spatial
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}
                for _ in range(self._num_states)]

    _num_states = 1

    def _conv_gates(self, F, inputs, prev_h, i2h_weight, h2h_weight,
                    i2h_bias, h2h_bias):
        total = self._hidden_channels * self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel,
                            pad=self._i2h_pad,
                            dilate=self._i2h_dilate,
                            num_filter=total)
        h2h = F.Convolution(prev_h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel,
                            pad=self._h2h_pad,
                            dilate=self._h2h_dilate,
                            num_filter=total)
        return i2h, h2h

    def _split(self, F, x):
        return list(F.SliceChannel(x, num_outputs=self._num_gates,
                                   axis=1)) if self._num_gates > 1 \
            else [x]


class _ConvRNNCell(_ConvCellBase):
    _num_gates = 1
    _num_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        i2h, h2h = self._conv_gates(F, inputs, prev, i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMCell(_ConvCellBase):
    _num_gates = 4
    _num_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h, prev_c = states
        i2h, h2h = self._conv_gates(F, inputs, prev_h, i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        in_g, forget_g, cell_g, out_g = self._split(F, gates)
        i = F.sigmoid(in_g)
        f = F.sigmoid(forget_g)
        c_tilde = F.Activation(cell_g, act_type=self._activation)
        o = F.sigmoid(out_g)
        next_c = f * prev_c + i * c_tilde
        next_h = o * F.Activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_ConvCellBase):
    _num_gates = 3
    _num_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        i2h, h2h = self._conv_gates(F, inputs, prev, i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        i_r, i_z, i_n = self._split(F, i2h)
        h_r, h_z, h_n = self._split(F, h2h)
        reset = F.sigmoid(i_r + h_r)
        update = F.sigmoid(i_z + h_z)
        new = F.Activation(i_n + reset * h_n,
                           act_type=self._activation)
        out = (1.0 - update) * new + update * prev
        return out, [out]


def _make(cls, dims, doc):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", activation="tanh",
                 prefix=None, params=None):
        cls.__init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                     h2h_dilate=h2h_dilate,
                     i2h_weight_initializer=i2h_weight_initializer,
                     h2h_weight_initializer=h2h_weight_initializer,
                     i2h_bias_initializer=i2h_bias_initializer,
                     h2h_bias_initializer=h2h_bias_initializer,
                     dims=dims, activation=activation, prefix=prefix,
                     params=params)

    return type(doc, (cls,), {"__init__": __init__, "__doc__":
                              "%s (reference contrib.rnn.%s)."
                              % (doc, doc)})


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
