"""Experimental recurrent cells (reference gluon/contrib/rnn)."""
from . import conv_rnn_cell  # noqa: F401
from . import rnn_cell  # noqa: F401
from .conv_rnn_cell import *  # noqa: F401,F403
from .rnn_cell import LSTMPCell, VariationalDropoutCell  # noqa: F401
