"""DataLoader (reference: `python/mxnet/gluon/data/dataloader.py:26-111`).

The reference forks worker processes that decode samples and ship them
back through POSIX shared memory.  TPU-native design note: the heavy
per-sample work (image decode/augment) belongs on host CPU threads while
the chip runs ahead asynchronously, so this DataLoader defaults to a
thread pool (`num_workers`) + a prefetch queue; batches land as
committed host arrays ready for a single device transfer.  (The C++ IO
pipeline in `src/` takes over the decode path as it lands.)

`thread_pool=False` switches to FORKED WORKER PROCESSES (the
reference's model): right when the per-sample transform is
python-heavy (GIL-bound) rather than decode-heavy.  Workers batchify
to NUMPY (never touching jax/the device) and the parent does the
single host->device conversion.  Measured crossover on this host
(tests/test_gluon_data.py, crossover timing print):
a ~1 ms pure-python transform per sample is already ~2x faster with
2 processes than 2 threads; byte-decode workloads favor threads.
"""
from __future__ import annotations

import multiprocessing
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable, List, Optional

import numpy as np

from ...base import MXNetError
from ... import resilience as _res
from ...ndarray.ndarray import NDArray, array as nd_array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ...ndarray import stack

        return stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd_array(arr)


def _np_batchify(data):
    """Worker-side batchify: pure numpy (workers must never initialize
    jax — the device belongs to the parent)."""
    if isinstance(data[0], NDArray):
        raise MXNetError(
            "process workers (thread_pool=False) need datasets that "
            "return numpy/python samples — NDArray samples would pull "
            "the device runtime into the forked worker; use "
            "thread_pool=True (default) or return numpy from "
            "__getitem__")
    if isinstance(data[0], tuple):
        return tuple(_np_batchify(list(i)) for i in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


_WORKER_DATASET = None


def _worker_init(dataset):
    global _WORKER_DATASET
    _WORKER_DATASET = dataset


#: Sentinel tag a forked worker returns instead of raising: exceptions
#: must cross the pickle boundary with their ORIGINAL traceback intact
#: (pickling arbitrary exception objects can itself fail, which the
#: reference dataloader turns into a deadlocked iterator).
_ERR_TAG = "__mxtpu_worker_error__"


class _WorkerLost(Exception):
    """A pool worker died (SIGKILL/segfault) while holding a batch —
    its result will never arrive."""


def _worker_fn(args):
    idx_batch, batchify = args
    try:
        _res.maybe_fault("dataloader")
        samples = [_WORKER_DATASET[i] for i in idx_batch]
        return batchify(samples)
    except Exception as e:
        return (_ERR_TAG, type(e).__name__, str(e),
                traceback.format_exc())


def _pool_pids(pool):
    return {p.pid for p in getattr(pool, "_pool", [])}


def _await_async(pool, res, submit_pids, poll: float = 0.2,
                 grace: float = 2.0):
    """``res.get()`` that cannot hang forever: a worker that dies
    (SIGKILL/segfault) is silently replaced by the pool's maintenance
    thread and the task it held is dropped — the naive ``.get()`` then
    blocks for good.  A death is detected by comparing the pool's pid
    SET against ``submit_pids``, the set captured when this batch was
    SUBMITTED (replacement swaps a pid, observable even if the death
    happened while the parent was off yielding earlier batches); if
    the result is still pending ``grace`` seconds after a death is
    seen, it is declared lost (:class:`_WorkerLost`) so the caller
    resubmits."""
    death_seen = None
    while True:
        try:
            return res.get(poll)
        except multiprocessing.TimeoutError:
            procs = list(getattr(pool, "_pool", []))
            cur = {p.pid for p in procs}
            if cur != submit_pids or any(not p.is_alive() for p in procs):
                if death_seen is None:
                    death_seen = time.monotonic()
            if death_seen is not None and \
                    time.monotonic() - death_seen >= grace:
                if res.ready():  # arrived at the last moment
                    return res.get(0)
                raise _WorkerLost()


def _to_nd(batch):
    if isinstance(batch, tuple):
        return [_to_nd(b) for b in batch]
    if isinstance(batch, np.ndarray):
        return nd_array(batch)
    return batch


class DataLoader(object):
    def __init__(self, dataset: Dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 prefetch=None, thread_pool=True, seed=None):
        self._dataset = dataset
        self._seed = seed
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset), seed=seed) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise MXNetError("batch_size/shuffle/sampler/last_batch must "
                             "not be set when batch_sampler is given")
        self._sampler = sampler if sampler is not None else \
            getattr(batch_sampler, "_sampler", None)
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        # position bookkeeping for mx.checkpoint: (epoch, batches
        # handed to the consumer this epoch) — see state()/set_state()
        self._epoch = 0
        self._pos_epoch = 0
        self._pos_batch = 0
        self._resume = None

    # -- checkpointable position (docs/checkpoint.md) ---------------------
    def state(self):
        """Current position as a JSON-able dict: ``epoch``, ``batch``
        (batches already handed out this epoch — the index the NEXT
        batch would have), and the shuffle ``seed``.  With a seeded
        sampler, `set_state` on a fresh loader re-enters the identical
        batch stream mid-epoch."""
        return {"epoch": int(self._pos_epoch),
                "batch": int(self._pos_batch),
                "seed": self._seed}

    def set_state(self, state) -> None:
        """Arm deterministic re-entry at a `state()` position: the next
        `__iter__` shuffles for that epoch (seeded sampler) and skips
        the first ``batch`` index-batches WITHOUT touching the dataset."""
        if state is None:
            return
        saved_seed = state.get("seed")
        if saved_seed is not None and self._seed is not None and \
                saved_seed != self._seed:
            raise MXNetError(
                "DataLoader.set_state: shuffle seed mismatch (saved %r, "
                "this loader %r) — the restored position would replay a "
                "different batch stream" % (saved_seed, self._seed))
        self._resume = (int(state.get("epoch", 0)),
                        int(state.get("batch", 0)))

    def _make_batch(self, indices):
        _res.maybe_fault("dataloader")
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        # input-wait gauge (mx.health / docs/observability.md): time
        # from the consumer ASKING for the next batch (this generator
        # resuming) to the batch being ready — the host-input wait that
        # separates "pipeline-bound" from "device-bound" step time
        from ... import telemetry as _tel

        if self._resume is not None:
            epoch, skip = self._resume
            self._resume = None
        else:
            epoch, skip = self._epoch, 0
        if getattr(self._sampler, "seed", None) is not None:
            # loader is authoritative over the shuffle epoch so an
            # abandoned iterator or a restore can't desync the stream
            self._sampler.set_epoch(epoch)
        self._epoch = epoch
        self._pos_epoch = epoch
        self._pos_batch = skip
        it = self._iter_impl(skip)
        # MXTPU_PREFETCH_DEVICE=N (an `mx.tune` registered knob):
        # a lookahead thread pulls the NEXT batch and completes its
        # host->device transfer while the consumer computes on the
        # current one, so the input_wait gauge below measures only
        # what the pipeline could NOT hide
        depth = int(os.environ.get("MXTPU_PREFETCH_DEVICE", "0") or 0)
        if depth > 0:
            it = self._device_prefetch_iter(it, depth)
        while True:
            # nesting-guarded scope: when this fetch itself drives an
            # inner DataIter (dataset backed by one), only THIS
            # outermost layer records — no double count
            try:
                with _tel.input_wait():
                    batch = next(it)
            except StopIteration:
                self._epoch = epoch + 1
                return
            self._pos_batch += 1
            yield batch

    @staticmethod
    def _force_device(batch):
        """Complete a batch's host->device transfer (NDArray creation
        dispatches ``device_put`` asynchronously; blocking HERE, on
        the prefetch thread, is the whole point — the consumer thread
        receives a device-resident, ready batch)."""
        if isinstance(batch, (list, tuple)):
            for b in batch:
                DataLoader._force_device(b)
        elif isinstance(batch, NDArray):
            batch.wait_to_read()
        return batch

    def _device_prefetch_iter(self, it, depth: int):
        """Async host->device prefetch: a daemon thread runs ``depth``
        batches ahead, batchifying AND device-transferring each, with a
        bounded queue for backpressure.  Errors cross over and re-raise
        in the consumer; an abandoned consumer unblocks the worker via
        the stop event (the queue put polls it)."""
        from ... import profiler as _prof

        out_q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        stop = threading.Event()
        _DONE = object()

        def worker():
            try:
                for batch in it:
                    self._force_device(batch)
                    _prof.inc_stat("dataloader_device_prefetch")
                    while not stop.is_set():
                        try:
                            out_q.put((batch, None), timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                out_q.put((_DONE, None))
            except BaseException as e:  # surface in the consumer
                # The sentinel put must survive a full queue: dropping
                # it (the old `except queue.Full: pass`) left the
                # consumer blocked forever on `out_q.get()` — the error
                # path retries against the stop event exactly like the
                # normal path (tests/test_gluon_data.py regression).
                while not stop.is_set():
                    try:
                        out_q.put((_DONE, e), timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True,
                             name="mxtpu-device-prefetch")
        t.start()
        try:
            while True:
                batch, err = out_q.get()
                if batch is _DONE:
                    if err is not None:
                        raise err
                    return
                yield batch
        finally:
            stop.set()

    def _iter_impl(self, skip: int = 0):
        if self._num_workers == 0:
            it = iter(self._batch_sampler)
            for _ in range(skip):  # resume re-entry: index-only skip
                if next(it, None) is None:
                    return
            for indices in it:
                # inline path: full retry policy on transient faults
                yield _res.run_with_retry(
                    "dataloader", lambda idx=indices: self._make_batch(idx))
            return
        if self._thread_pool:
            yield from self._threaded_iter(skip)
        else:
            yield from self._process_iter(skip)

    def _process_iter(self, skip: int = 0):
        """Forked worker processes (reference dataloader.py:26-111
        model): per-sample transforms run GIL-free; workers ship numpy
        batches back (pickle), the parent converts once per batch.
        Custom `batchify_fn` runs IN the worker and must be picklable
        and numpy-only; the default numpy batchify is swapped in for
        the NDArray one automatically.

        Resilience: a worker EXCEPTION comes back as a tagged tuple
        carrying the original traceback (never a deadlock), the batch
        is retried once in a fresh worker, and a second failure raises
        with that traceback attached.  A worker DEATH (SIGKILL /
        segfault — the pool silently loses the batch and the naive
        ``.get()`` hangs forever) is detected by polling worker
        liveness; the lost batch is resubmitted once to the
        auto-replenished pool."""
        batchify = self._batchify_fn
        if batchify is default_batchify_fn:
            batchify = _np_batchify
        ctx = multiprocessing.get_context("fork")
        batches = list(self._batch_sampler)[skip:]
        pool = ctx.Pool(min(self._num_workers, max(1, len(batches))),
                        initializer=_worker_init,
                        initargs=(self._dataset,))
        # windowed submission: same backpressure contract as the
        # threaded path — at most max(prefetch, num_workers) batches
        # decoded ahead of the consumer
        window = max(self._prefetch, self._num_workers)

        def _submit(indices):
            # the pid set at submit time anchors death detection for
            # this batch (a worker may die while the parent is off
            # yielding earlier batches)
            return (indices,
                    pool.apply_async(_worker_fn, ((indices, batchify),)),
                    _pool_pids(pool))

        try:
            pending = []  # (indices, AsyncResult, submit-time pids)
            submit = 0
            while submit < len(batches) and len(pending) < window:
                pending.append(_submit(batches[submit]))
                submit += 1
            while pending:
                indices, res, pids = pending.pop(0)
                out = self._resolve_pooled(pool, batchify, indices, res,
                                           pids)
                if submit < len(batches):
                    pending.append(_submit(batches[submit]))
                    submit += 1
                yield _to_nd(out)
        finally:
            pool.terminate()
            pool.join()

    def _resolve_pooled(self, pool, batchify, indices, res, pids,
                        attempt=0):
        from ... import profiler as _prof

        try:
            out = _await_async(pool, res, pids)
        except _WorkerLost:
            if attempt >= 1:
                raise MXNetError(
                    "DataLoader worker process died twice while decoding "
                    "the same batch (indices %r) — giving up" % (indices,))
            _prof.inc_stat("dataloader_worker_respawn")
            retry = pool.apply_async(_worker_fn, ((indices, batchify),))
            return self._resolve_pooled(pool, batchify, indices, retry,
                                        _pool_pids(pool), attempt + 1)
        if isinstance(out, tuple) and len(out) == 4 and out[0] == _ERR_TAG:
            _, etype, emsg, tb = out
            if attempt >= 1:
                # fresh worker failed too: last resort is the parent
                # computing the batch itself under the full retry
                # policy; only then surface the ORIGINAL traceback
                try:
                    return _res.run_with_retry(
                        "dataloader", lambda: self._make_batch(indices))
                except Exception:
                    raise MXNetError(
                        "DataLoader worker raised %s: %s (retried in a "
                        "fresh worker and in the parent)\n"
                        "--- original worker traceback ---\n%s"
                        % (etype, emsg, tb))
            _prof.inc_stat("dataloader_worker_retry")
            retry = pool.apply_async(_worker_fn, ((indices, batchify),))
            return self._resolve_pooled(pool, batchify, indices, retry,
                                        _pool_pids(pool), attempt + 1)
        return out

    def _threaded_iter(self, skip: int = 0):
        """Thread-pool pipeline with bounded in-order prefetch."""
        batches = list(self._batch_sampler)[skip:]
        results: "queue.Queue" = queue.Queue()
        lock = threading.Lock()
        next_submit = [0]
        stop = threading.Event()
        # bound how far workers run ahead of the consumer
        budget = threading.Semaphore(max(self._prefetch, self._num_workers))

        def worker():
            while True:
                budget.acquire()
                if stop.is_set():
                    return
                with lock:
                    i = next_submit[0]
                    if i >= len(batches):
                        budget.release()
                        return
                    next_submit[0] += 1
                try:
                    out = self._make_batch(batches[i])
                    results.put((i, out, None))
                except Exception as e:  # propagate to consumer
                    results.put((i, None, e))

        n_threads = min(self._num_workers, max(1, len(batches)))
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        try:
            want = 0
            stash = {}
            got = 0
            while got < len(batches):
                while want not in stash:
                    i, out, err = results.get()
                    stash[i] = (out, err)
                out, err = stash.pop(want)
                if err is not None:
                    # retry the failed batch inline under the FULL
                    # retry policy (a single bare retry would lose to a
                    # second transient fault); persistent failure
                    # surfaces with the original worker error chained
                    from ... import profiler as _prof

                    _prof.inc_stat("dataloader_worker_retry")
                    try:
                        out = _res.run_with_retry(
                            "dataloader",
                            lambda w=want: self._make_batch(batches[w]))
                    except Exception:
                        raise MXNetError(
                            "DataLoader batch %d failed twice; original "
                            "worker error: %r" % (want, err)) from err
                yield out
                budget.release()  # consumer consumed: allow another ahead
                want += 1
                got += 1
        finally:
            # wake any blocked workers so they exit even if the consumer
            # abandoned the generator early or a batch raised
            stop.set()
            for _ in threads:
                budget.release()

    def __len__(self):
        return len(self._batch_sampler)
