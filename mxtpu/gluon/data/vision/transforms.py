"""Vision transforms (reference: `python/mxnet/gluon/data/vision/
transforms.py`): Compose, Cast, ToTensor, Normalize, Resize, CenterCrop,
RandomResizedCrop, RandomFlip*, RandomBrightness/Contrast (subset)."""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn.basic_layers import HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast"]


class Compose(HybridSequential):
    def __init__(self, transforms):
        super().__init__()
        with self.name_scope():
            for t in transforms:
                self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    def __init__(self):
        super().__init__()

    def hybrid_forward(self, F, x):
        return F._image_to_tensor(x)


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean if isinstance(mean, (list, tuple)) else (mean,)
        self._std = std if isinstance(std, (list, tuple)) else (std,)

    def hybrid_forward(self, F, x):
        return F._image_normalize(x, mean=tuple(self._mean),
                                  std=tuple(self._std))


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        from .... import ndarray as _nd

        if isinstance(self._size, tuple):
            size = self._size
        elif self._keep:
            # short-side resize preserving aspect ratio
            hh, ww = x.shape[-3], x.shape[-2]
            if ww < hh:
                size = (self._size, int(round(hh * self._size / ww)))
            else:
                size = (int(round(ww * self._size / hh)), self._size)
        else:
            size = (self._size, self._size)
        return _nd._image_resize(x, size=size, interp=self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, tuple) else (size, size)

    def forward(self, x):
        from .... import ndarray as _nd

        w, h = self._size
        hh, ww = x.shape[-3], x.shape[-2]
        y0 = max((hh - h) // 2, 0)
        x0 = max((ww - w) // 2, 0)
        return _nd._image_crop(x, x=x0, y=y0, width=min(w, ww),
                               height=min(h, hh))


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, tuple) else (size, size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        from .... import ndarray as _nd

        hh, ww = x.shape[-3], x.shape[-2]
        area = hh * ww
        for _ in range(10):
            target_area = _pyrandom.uniform(*self._scale) * area
            ar = _pyrandom.uniform(*self._ratio)
            w = int(round(np.sqrt(target_area * ar)))
            h = int(round(np.sqrt(target_area / ar)))
            if w <= ww and h <= hh:
                x0 = _pyrandom.randint(0, ww - w)
                y0 = _pyrandom.randint(0, hh - h)
                crop = _nd._image_crop(x, x=x0, y=y0, width=w, height=h)
                return _nd._image_resize(crop, size=self._size,
                                         interp=self._interp)
        return _nd._image_resize(x, size=self._size, interp=self._interp)


class RandomFlipLeftRight(HybridBlock):
    def __init__(self):
        super().__init__()

    def hybrid_forward(self, F, x):
        return F._image_random_flip_left_right(x)


class RandomFlipTopBottom(HybridBlock):
    def __init__(self):
        super().__init__()

    def hybrid_forward(self, F, x):
        return F._image_random_flip_top_bottom(x)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._brightness = brightness

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._brightness, self._brightness)
        return x * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._contrast = contrast

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._contrast, self._contrast)
        gray = x.astype("float32").mean()
        return x.astype("float32") * alpha + gray * (1 - alpha)
