"""Vision datasets (reference: `python/mxnet/gluon/data/vision/datasets.py`).

MNIST/FashionMNIST/CIFAR10/CIFAR100 read the standard file formats from a
local root.  This environment has no network egress, so when the files are
absent the datasets fall back to a DETERMINISTIC synthetic sample set with
the right shapes/dtypes/classes (documented deviation — lets every
training example and test run without downloads).
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ....base import MXNetError
from ....ndarray.ndarray import NDArray, array as nd_array, from_numpy
from ..dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100"]


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    # class-dependent means so models can actually learn from it
    labels = rng.randint(0, num_classes, n).astype(np.int32)
    base = rng.rand(num_classes, *shape).astype(np.float32)
    imgs = (base[labels] * 128 + rng.rand(n, *shape) * 64).astype(np.uint8)
    return imgs, labels


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        x = from_numpy(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (reference datasets.MNIST). Reads idx-ubyte(.gz) files from
    `root` when present; synthetic fallback otherwise."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
    _shape = (28, 28, 1)
    _classes = 10
    _synthetic_n = 2048

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_idx(self, img_path, lbl_path):
        opener = gzip.open if img_path.endswith(".gz") else open
        with opener(lbl_path, "rb") as f:
            magic, num = struct.unpack(">II", f.read(8))
            label = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)
        with opener(img_path, "rb") as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
            data = data.reshape(num, rows, cols, 1)
        return data, label

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        for ext in ("", ".gz"):
            img = os.path.join(self._root, files[0] + ext)
            lbl = os.path.join(self._root, files[1] + ext)
            if os.path.exists(img) and os.path.exists(lbl):
                self._data, self._label = self._read_idx(img, lbl)
                return
        n = self._synthetic_n if self._train else self._synthetic_n // 4
        self._data, self._label = _synthetic_images(
            n, self._shape, self._classes, seed=42 if self._train else 43)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (reference datasets.CIFAR10). Reads the binary batches from
    `root` when present; synthetic fallback otherwise."""

    _shape = (32, 32, 3)
    _classes = 10
    _synthetic_n = 2048

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"), train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        row = 1 + self._shape[0] * self._shape[1] * self._shape[2]
        data = raw.reshape(-1, row)
        label = data[:, 0].astype(np.int32)
        imgs = data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return imgs, label

    def _get_data(self):
        base = os.path.join(self._root, "cifar-10-batches-bin")
        if self._train:
            names = ["data_batch_%d.bin" % i for i in range(1, 6)]
        else:
            names = ["test_batch.bin"]
        paths = [os.path.join(base, n) for n in names]
        if all(os.path.exists(p) for p in paths):
            parts = [self._read_batch(p) for p in paths]
            self._data = np.concatenate([p[0] for p in parts])
            self._label = np.concatenate([p[1] for p in parts])
            return
        n = self._synthetic_n if self._train else self._synthetic_n // 4
        self._data, self._label = _synthetic_images(
            n, self._shape, self._classes, seed=44 if self._train else 45)


class CIFAR100(CIFAR10):
    _classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"), fine_label=False,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        base = os.path.join(self._root, "cifar-100-binary")
        name = "train.bin" if self._train else "test.bin"
        path = os.path.join(base, name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                raw = np.frombuffer(f.read(), dtype=np.uint8)
            row = 2 + 32 * 32 * 3
            data = raw.reshape(-1, row)
            self._label = data[:, 1 if self._fine else 0].astype(np.int32)
            self._data = data[:, 2:].reshape(-1, 3, 32, 32) \
                .transpose(0, 2, 3, 1)
            return
        n = self._synthetic_n if self._train else self._synthetic_n // 4
        self._data, self._label = _synthetic_images(
            n, self._shape, self._classes, seed=46 if self._train else 47)
