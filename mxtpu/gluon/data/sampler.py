"""Samplers (reference: `python/mxnet/gluon/data/sampler.py`)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]


class Sampler(object):
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Uniform shuffle.  With ``seed`` set the permutation of epoch ``e``
    is a pure function of ``(seed, e)`` — `mx.checkpoint` records
    ``(seed, epoch, batch)`` as the DataLoader position and a resumed
    run regenerates the *identical* index stream mid-epoch; with
    ``seed=None`` (default) the legacy global-numpy shuffle is kept."""

    def __init__(self, length, seed=None):
        self._length = length
        self._seed = seed
        self._epoch = 0

    @property
    def seed(self):
        return self._seed

    def set_epoch(self, epoch) -> None:
        """Pin which epoch the NEXT `__iter__` shuffles for (resume
        re-entry; no-op for unseeded samplers, whose stream is not
        reconstructible anyway)."""
        self._epoch = int(epoch)

    def __iter__(self):
        if self._seed is None:
            indices = np.random.permutation(self._length)
        else:
            rng = np.random.RandomState(
                (int(self._seed) + 0x9E3779B1 * self._epoch)
                % (2 ** 31 - 1))
            indices = rng.permutation(self._length)
        self._epoch += 1
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Wrap a sampler into batches; last_batch in {keep, discard, rollover}
    (reference BatchSampler)."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(
                "last_batch must be one of keep/discard/rollover, got %r"
                % last_batch)

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        return (len(self._sampler) + len(self._prev)) // self._batch_size
