"""Block / HybridBlock / SymbolBlock (reference: `python/mxnet/gluon/
block.py:127,671,952`).

Same user model as the reference: Blocks compose imperatively; a
HybridBlock can `hybridize()`, which traces `hybrid_forward` with Symbol
proxies and compiles the whole graph into a CachedOp (`block.py:748-785`) —
here the CachedOp is a single jitted XLA module (see mxtpu/cached_op.py),
which is the TPU-native payoff: one compiled computation per network
instead of per-op dispatch.
"""
from __future__ import annotations

import copy
import re
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd_mod
from .. import symbol as sym_mod
from ..symbol.symbol import NameManager, Symbol
from ..cached_op import CachedOp
from .parameter import (Parameter, ParameterDict, DeferredInitializationError,
                        tensor_types)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


def _flatten(args, fmt_hint="input"):
    """Flatten nested lists/tuples of arrays into a flat list + format tree
    (reference `block.py` _flatten)."""
    if isinstance(args, (NDArray, Symbol)):
        return [args], 0
    if isinstance(args, (list, tuple)):
        flat, fmts = [], []
        for a in args:
            f, fmt = _flatten(a, fmt_hint)
            flat.extend(f)
            fmts.append(fmt)
        return flat, fmts
    if args is None:
        return [], -1
    raise MXNetError("cannot flatten argument of type %s in %s"
                     % (type(args), fmt_hint))


def _regroup(flat, fmt):
    """Inverse of _flatten. Returns (structure, remaining_flat)."""
    if fmt == 0:
        return flat[0], flat[1:]
    if fmt == -1:
        return None, flat
    structure = []
    for f in fmt:
        item, flat = _regroup(flat, f)
        structure.append(item)
    return structure, flat


class _TraceNames(NameManager):
    """NameManager active while tracing one block's ``hybrid_forward``:
    anonymous ops get the block's ABSOLUTE prefix ("mlp_fc1_"), so the
    traced graph — and through `mx.inspect`'s per-node `named_scope`,
    the HLO op metadata and device traces — resolves to model layers
    instead of bare "fullyconnected2" counters.  Counters are shared
    with the enclosing manager (one dict per trace), so a
    weight-shared block called twice still yields unique node names.
    Explicit names pass through untouched (unlike `mx.name.Prefix`):
    Parameter.var() and user-named ops must keep their exact names or
    `_build_cache`'s arg mapping breaks."""

    def __init__(self, prefix):
        super().__init__()
        self._counter = NameManager.current()._counter
        self._prefix = prefix

    def get(self, name, hint):
        if name:
            return name
        return self._prefix + super().get(None, hint)


class _BlockScope(object):
    """Name scoping for blocks (reference `block.py:35`)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from ..symbol.symbol import NameManager

                prefix = NameManager.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        from ..symbol.symbol import NameManager

        self._name_scope = NameManager()
        self._name_scope.__enter__()
        return self

    def __exit__(self, *args):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(*args)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


class Block(object):
    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: List = []
        self._forward_pre_hooks: List = []

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(str(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Auto-register children and parameters; reassignment
        unregisters the previous Block/Parameter bound to the name
        (reference `block.py:218`)."""
        if hasattr(self, "_children"):
            if isinstance(value, Block):
                self._children[name] = value
            elif name in self._children:
                del self._children[name]
        if hasattr(self, "_reg_params"):
            if isinstance(value, Parameter):
                self._reg_params[name] = value
            elif name in self._reg_params:
                del self._reg_params[name]
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret._params = OrderedDict(
                (name, value) for name, value in self.params.items()
                if pattern.match(name))
        for child in self._children.values():
            child_params = child.collect_params(select)
            ret.update(child_params)
        return ret

    def child_blocks(self):
        return list(self._children.values())

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as _init_mod

        self.collect_params().initialize(init or _init_mod.Uniform(), ctx,
                                         verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    # -- persistence ------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        from ..ndarray import save as nd_save

        nd_save(filename, {k: v._reduce() if hasattr(v, "_reduce")
                           else v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError(
                        "Parameter %r is missing in file %r" % (name,
                                                                filename))
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(
                        "Parameter %r in file %r is not in this Block"
                        % (name, filename))
                continue
            param = params[name]
            if param._data is None and param._deferred_init == () and \
                    param._shape is None:
                param._shape = tuple(loaded[name].shape)
            if param._data is None and not param._deferred_init:
                param.initialize(ctx=ctx or [current_context()])
            param.set_data(loaded[name])

    save_params = save_parameters
    load_params = load_parameters

    def _collect_params_with_prefix(self, prefix="") -> Dict[str, Parameter]:
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # -- execution --------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference block.summary)."""
        summary = []

        def walk(block, depth):
            pcount = sum(int(np.prod(p.shape)) for p in
                         block._reg_params.values()
                         if p.shape and all(s > 0 for s in p.shape))
            summary.append(("  " * depth + block.__class__.__name__, pcount))
            for c in block._children.values():
                walk(c, depth + 1)

        walk(self, 0)
        lines = ["%-40s %12d" % row for row in summary]
        total = sum(r[1] for r in summary)
        out = "\n".join(lines) + "\nTotal params: %d" % total
        print(out)
        return out


def _indent(s, num_spaces):
    lines = s.split("\n")
    first = lines.pop(0)
    return first + ("\n" + "\n".join(" " * num_spaces + line
                                     for line in lines) if lines else "")


class HybridBlock(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op: Optional[CachedOp] = None
        self._cached_meta = None
        self._flags = []

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = list(kwargs.items())
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def _clear_cached_op(self):
        self._cached_op = None
        self._cached_meta = None

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise MXNetError(
                "children of a HybridBlock must be HybridBlocks; got %s"
                % type(block))
        super().register_child(block, name)
        self._clear_cached_op()

    # -- tracing ----------------------------------------------------------
    def _trace_symbol(self, *args):
        """Trace hybrid_forward with Symbol proxies; returns
        (out_sym, out_fmt, in_fmt)."""
        flat, in_fmt = _flatten(list(args), "input")
        data_syms = [sym_mod.var("data%d" % i) for i in range(len(flat))]
        structured, _ = _regroup(list(data_syms), in_fmt)
        with _TraceNames(self.prefix):
            out = self._call_hybrid(sym_mod, structured, trace=True)
        out_flat, out_fmt = _flatten(out, "output")
        out_sym = out_flat[0] if len(out_flat) == 1 else \
            sym_mod.Group(out_flat)
        return out_sym, out_fmt, in_fmt

    def _build_cache(self, *args):
        """Trace hybrid_forward with Symbol proxies (reference
        `block.py:748`)."""
        out_sym, out_fmt, in_fmt = self._trace_symbol(*args)
        self._out_fmt = out_fmt
        self._in_fmt = in_fmt
        # mx.tune: with MXTPU_TUNE=apply, install this graph's
        # persisted tuning config BEFORE the CachedOp builds, so the
        # knobs (passes subset, buckets, donation, ...) shape the
        # traced programs.  One bool check when off (the default).
        from .. import tune as _tune

        if _tune.apply_enabled():
            _tune.maybe_apply(
                symbol=out_sym,
                profile=_tune.profile_of_shapes(
                    [("data%d" % i, a.shape) for i, a in enumerate(args)
                     if hasattr(a, "shape")]),
                site="hybridize")
        # "program_name" keys the mx.inspect registry record by THIS
        # block, so retraces across cache rebuilds stay one program
        self._cached_op = CachedOp(
            out_sym, list(self._flags) + [("program_name", self.name)])
        # map graph arguments to data slots / Parameters
        arg_names = self._cached_op._arg_names
        aux_names = self._cached_op._aux_names
        by_name = {p.name: p for p in self._collect_all_params()}
        self._cached_arg_map = []
        for name in arg_names:
            m = re.match(r"^data(\d+)$", name)
            if m:
                self._cached_arg_map.append(int(m.group(1)))
            else:
                if name not in by_name:
                    raise MXNetError("traced graph references unknown "
                                     "parameter %r" % name)
                self._cached_arg_map.append(by_name[name])
        self._cached_aux = [by_name[name] for name in aux_names]
        # the data slots are the bucketable (ragged-batch) args
        self._cached_op.set_data_indices(
            [pos for pos, slot in enumerate(self._cached_arg_map)
             if isinstance(slot, int)])

    def _collect_all_reg_params(self):
        out = dict(self._reg_params)
        for c in self._children.values():
            if isinstance(c, HybridBlock):
                out.update(c._collect_all_reg_params())
        return out

    def _collect_all_params(self) -> List[Parameter]:
        seen = []
        for p in self.collect_params().values():
            seen.append(p)
        return seen

    def _call_hybrid(self, F, inputs, trace=False):
        """Invoke hybrid_forward with this block's own params as kwargs."""
        if F is sym_mod:
            kwargs = {name: p.var() for name, p in self._reg_params.items()}
        else:
            # pick the parameter copy on the input's device (reference
            # HybridBlock.forward: `i.data(ctx)` per replica)
            ctx = None
            flat_in, _ = _flatten(list(inputs), "input")
            for a in flat_in:
                if isinstance(a, NDArray):
                    ctx = a.ctx
                    break
            try:
                kwargs = {name: p.data(ctx) for name, p in
                          self._reg_params.items()}
            except DeferredInitializationError:
                self._deferred_infer_shape(*inputs)
                for p in self._collect_all_reg_params().values():
                    p._finish_deferred_init()
                kwargs = {name: p.data(ctx) for name, p in
                          self._reg_params.items()}
        return self.hybrid_forward(F, *inputs, **kwargs)

    def _deferred_infer_shape(self, *args):
        """Infer deferred parameter shapes by tracing symbolically and
        running infer_shape with the data shapes (reference
        `block.py:_infer_attrs`)."""
        try:
            out_sym, _, _ = self._trace_symbol(*args)
            flat_args, _ = _flatten(list(args), "input")
            shape_kwargs = {"data%d" % i: a.shape
                            for i, a in enumerate(flat_args)}
            arg_shapes, _, aux_shapes = out_sym.infer_shape_partial(
                **shape_kwargs)
            all_params = {p.name: p for p in self._collect_all_params()}
            for name, shape in zip(out_sym.list_arguments(), arg_shapes):
                if name in all_params and shape is not None:
                    all_params[name].shape = shape
            for name, shape in zip(out_sym.list_auxiliary_states(),
                                   aux_shapes):
                if name in all_params and shape is not None:
                    all_params[name].shape = shape
        except DeferredInitializationError:
            raise
        except MXNetError as e:
            raise MXNetError("deferred shape inference failed: %s" % e) from e

    # -- execution --------------------------------------------------------
    def forward(self, x, *args):
        first = x
        while isinstance(first, (list, tuple)) and first:
            first = first[0]
        if isinstance(first, NDArray):
            if self._active:
                if self._cached_op is None:
                    # finish deferred param init first (needs shapes)
                    try:
                        for p in self._collect_all_reg_params().values():
                            p.data()
                    except (DeferredInitializationError, MXNetError):
                        self._deferred_infer_shape(x, *args)
                        for p in self._collect_all_params():
                            p._finish_deferred_init()
                    self._build_cache(x, *args)
                return self._run_cached(x, *args)
            return self._call_hybrid(nd_mod, [x] + list(args))
        if isinstance(first, Symbol):
            with _TraceNames(self.prefix):
                return self._call_hybrid(sym_mod, [x] + list(args))
        raise MXNetError("HybridBlock input must be NDArray or Symbol, got %s"
                         % type(first))

    def _run_cached(self, *args):
        flat_args, in_fmt = _flatten(list(args), "input")
        if in_fmt != self._in_fmt:
            self._build_cache(*args)  # input structure changed: retrace
            flat_args, _ = _flatten(list(args), "input")
        inputs = []
        for slot in self._cached_arg_map:
            if isinstance(slot, int):
                inputs.append(flat_args[slot])
            else:
                inputs.append(slot.data())
        aux = [p.data() for p in self._cached_aux]
        out = self._cached_op(inputs, aux)
        structured, _ = _regroup(list(out), self._out_fmt)
        return structured

    def forward_fused(self, x, *args):
        """Score K batches in ONE compiled program.

        Every input carries a leading K dimension over the traced batch
        shape (e.g. trace with (B, 3, H, W), call with (K, B, 3, H, W));
        returns outputs stacked the same way.  Amortizes per-dispatch
        latency exactly like FusedTrainLoop does for training — see
        CachedOp.call_fused.  The block must be hybridized; the cache is
        built from the first batch row if absent."""
        if not self._active:
            raise MXNetError("forward_fused requires hybridize()")
        if self._cached_op is None:
            # build the cache from batch row 0 of every input leaf —
            # sliced per LEAF (a top-level [x][0] would grab the first
            # structure element of a list input, not a batch row) and
            # under pause() so the warm-up forward can't record a tape
            # or write train-mode BN stats whatever scope the caller
            # is in (call_fused itself never touches aux)
            from .. import autograd as _ag

            flat0, fmt0 = _flatten([x] + list(args), "input")
            rows, _ = _regroup([a[0] for a in flat0], fmt0)
            with _ag.pause():
                self.forward(rows[0], *rows[1:])
        flat_args, in_fmt = _flatten([x] + list(args), "input")
        if in_fmt != self._in_fmt:
            raise MXNetError("forward_fused input structure does not "
                             "match the traced structure %r" % (self._in_fmt,))
        inputs = []
        stacked_idx = []
        for pos, slot in enumerate(self._cached_arg_map):
            if isinstance(slot, int):
                inputs.append(flat_args[slot])
                stacked_idx.append(pos)
            else:
                inputs.append(slot.data())
        aux = [p.data() for p in self._cached_aux]
        out = self._cached_op.call_fused(inputs, aux,
                                         stacked_idx=stacked_idx)
        structured, _ = _regroup(list(out), self._out_fmt)
        return structured

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Per-layer summary.  With example ``inputs`` (NDArrays or
        shape tuples) the block is traced symbolically and the call
        delegates to :func:`mxtpu.visualization.print_summary` — layer
        table with output shapes, param counts, and the XLA FLOPs
        column (plus the registry's whole-program figures when this
        block's compiled program exists in ``mx.inspect``).  Without
        inputs, falls back to the plain Block walk."""
        if not inputs:
            return super().summary()
        example = [nd_mod.zeros(tuple(a)) if isinstance(a, (tuple, list))
                   else a for a in inputs]
        try:
            for p in self._collect_all_reg_params().values():
                p.data()
        except (DeferredInitializationError, MXNetError):
            self._deferred_infer_shape(*example)
            for p in self._collect_all_params():
                p._finish_deferred_init()
        if self._cached_op is not None:
            # reuse the live cache's symbol: its graph head is what the
            # mx.inspect registry keys on, so the compiled-program
            # footer (whole-program FLOPs / peak memory) resolves
            out_sym = self._cached_op.symbol
        else:
            out_sym, _, _ = self._trace_symbol(*example)
        flat, _ = _flatten(list(example), "input")
        shapes = {"data%d" % i: tuple(a.shape) for i, a in enumerate(flat)}
        from .. import visualization

        return visualization.print_summary(out_sym, shape=shapes)

    # -- AOT warmup --------------------------------------------------------
    def warmup(self, input_shapes, dtype="float32"):
        """AOT-compile the hybridized graph for the given data input
        shapes WITHOUT running a batch (`CachedOp.warmup`, built on
        ``jit(...).lower().compile()``).

        ``input_shapes`` is one signature — a shape tuple per data
        input, e.g. ``[(8, 3, 224, 224)]`` — or a list of signatures,
        e.g. one per serving bucket.  Parameters must be initialized;
        the cache is traced from dummy zeros of the first signature if
        absent.  With `MXTPU_COMPILE_CACHE` enabled, warmup on a warm
        process start deserializes from disk instead of compiling."""
        if not self._active:
            raise MXNetError("warmup requires hybridize()")
        sigs = list(input_shapes)
        if not sigs:
            raise MXNetError("warmup needs at least one input shape")
        if isinstance(sigs[0][0], int):
            sigs = [sigs]  # a single signature was passed
        if self._cached_op is None:
            dummies = [nd_mod.zeros(tuple(s), dtype=dtype)
                       for s in sigs[0]]
            try:
                for p in self._collect_all_reg_params().values():
                    p.data()
            except (DeferredInitializationError, MXNetError):
                self._deferred_infer_shape(*dummies)
                for p in self._collect_all_params():
                    p._finish_deferred_init()
            self._build_cache(*dummies)
        aux_specs = [p.data() for p in self._cached_aux]
        for sig in sigs:
            arg_specs = []
            for slot in self._cached_arg_map:
                if isinstance(slot, int):
                    arg_specs.append((tuple(sig[slot]), dtype))
                else:
                    arg_specs.append(slot.data())
            self._cached_op.warmup(arg_specs, aux_specs, dtype=dtype)
        return self

    # -- export -----------------------------------------------------------
    def export(self, path, epoch=0):
        """Save symbol + params like the reference `block.py:868`
        (`path-symbol.json`, `path-%04d.params`)."""
        if self._cached_op is None:
            raise MXNetError("run forward at least once under hybridize() "
                             "before export")
        self._cached_op.symbol.save("%s-symbol.json" % path)
        arg_dict = {}
        for slot in self._cached_arg_map:
            if isinstance(slot, Parameter):
                arg_dict["arg:" + slot.name] = slot.data()
        for p in self._cached_aux:
            arg_dict["aux:" + p.name] = p.data()
        from ..ndarray import save as nd_save

        nd_save("%s-%04d.params" % (path, epoch), arg_dict)


class SymbolBlock(HybridBlock):
    """Wrap an arbitrary Symbol as a Block (reference `block.py:952`)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [s.name for s in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        # register under the ORIGINAL graph names (no prefix): the symbol
        # owns the naming here, matching the reference's SymbolBlock
        for name in arg_names:
            if name not in self._input_names and \
                    name not in self.params._params:
                self.params._params[name] = Parameter(
                    name, allow_deferred_init=True)
        for name in aux_names:
            if name not in self.params._params:
                self.params._params[name] = Parameter(
                    name, grad_req="null", allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        symbol = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        block = SymbolBlock(symbol, inputs)
        if param_file is not None:
            from ..ndarray import load as nd_load

            loaded = nd_load(param_file)
            by_name = {}
            for k, v in loaded.items():
                by_name[k.replace("arg:", "").replace("aux:", "")] = v
            for name, p in block.params.items():
                if name in by_name:
                    p._shape = tuple(by_name[name].shape)
                    p.initialize(ctx=ctx or [current_context()])
                    p.set_data(by_name[name])
        return block

    def forward(self, x, *args):
        if not isinstance(x, NDArray):
            raise MXNetError("SymbolBlock input must be NDArray")
        if self._cached_op is None:
            self._build_symbol_cache(len(args) + 1)
        return self._run_cached(x, *args)

    def _build_symbol_cache(self, n_inputs):
        self._cached_op = CachedOp(self._symbol,
                                   (("program_name", self.name),))
        by_name = {p.name: p for p in self.params.values()}
        self._cached_arg_map = []
        for i, name in enumerate(self._cached_op._arg_names):
            if name in self._input_names:
                self._cached_arg_map.append(self._input_names.index(name))
            else:
                self._cached_arg_map.append(by_name[name])
        self._cached_aux = [by_name[n] for n in self._cached_op._aux_names]
        self._cached_op.set_data_indices(
            [pos for pos, slot in enumerate(self._cached_arg_map)
             if isinstance(slot, int)])
        n_out = len(self._symbol.list_outputs())
        self._out_fmt = 0 if n_out == 1 else [0] * n_out
        self._in_fmt = [0] * n_inputs

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise MXNetError("SymbolBlock has no hybrid_forward")
