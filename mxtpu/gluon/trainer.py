"""Trainer (reference: `python/mxnet/gluon/trainer.py:27`).

Applies an Optimizer to a set of Parameters.  Reference flow
(`trainer.py:258`): `step()` -> `_allreduce_grads` (kvstore push/pull) ->
`_update` (fused optimizer ops per device).  Here single-device updates
run directly; multi-device/multi-chip gradient aggregation goes through
the kvstore ('device'/'tpu' = XLA collectives — see mxtpu/kvstore.py).
"""
from __future__ import annotations

import time as _time
from typing import Any, Dict, List, Optional

from ..base import MXNetError
from .. import checkpoint as _ckpt
from .. import health as _health
from .. import optimizer as opt_mod
from .. import perf as _perf
from .. import resilience as _res
from .. import telemetry as _tel
from .. import tracing as _tracing
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer(object):
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, sharding_plan=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/list")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError("invalid parameter %r" % p)
            self._param2idx[p.name] = i
            self._params.append(p)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._params_to_init = []
        self._contexts = None
        self._bad_step_guard = None  # built lazily from MXTPU_MAX_BAD_STEPS
        # mx.shard: an explicit plan, or the ambient one at _init_kvstore
        # time, engages the ZeRO-1 sharded updater over the replicas
        self._sharding_plan = sharding_plan
        self._zero1 = None
        # steps applied so far — the round anchor mx.checkpoint stamps
        # fleet snapshots with (restored on resume)
        self._num_steps = 0

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and list(optimizer_params) != ["rescale_grad"]:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            if contexts is not None and contexts != ctx:
                raise MXNetError("all Parameters must be on the same "
                                 "context set, got %s and %s"
                                 % (contexts, ctx))
            contexts = ctx
        return contexts or []

    def _init_kvstore(self):
        self._contexts = self._check_contexts()
        kv = self._kvstore_type
        if kv is None or (isinstance(kv, str) and kv in ("", "none")) or \
                len(self._contexts) <= 1 and kv in ("local", "device"):
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            from .. import kvstore as kv_mod

            self._kvstore = kv if not isinstance(kv, str) \
                else kv_mod.create(kv)
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = False
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.init(i, param.data())
        self._init_zero1()
        self._kv_initialized = True

    def _init_zero1(self):
        """Engage the ZeRO-1 sharded updater when a ShardingPlan is in
        force (ctor arg, active `mx.shard` scope, or MXTPU_SHARD env),
        there are multiple replica contexts, and the optimizer honors
        the elementwise-slicing contract.  One updater replaces the N
        per-replica full-state updaters (`docs/sharding.md`)."""
        from .. import sharding as _shard

        plan = self._sharding_plan if self._sharding_plan is not None \
            else _shard.current_plan()
        if (plan is None or self._update_on_kvstore
                or len(self._contexts) <= 1
                or not plan.shard_optimizer_state
                or not getattr(self._optimizer, "zero1_compatible", True)):
            self._zero1 = None
            return
        plan = plan.resolved(len(self._contexts))
        self._sharding_plan = plan
        idx2name = {i: p.name for i, p in enumerate(self._params)}
        self._zero1 = _shard.ZeRO1Updater(self._optimizer, plan,
                                          idx2name=idx2name)

    @property
    def live_workers(self):
        """Workers currently alive in the distributed group (elastic
        membership, `docs/elastic.md`); 1 without a kvstore.  The
        gradient-averaging contract needs NO adjustment when this
        drops: `dist_sync` rounds completed by fewer workers are
        rescaled server-side by ``nw0/live``, so the fixed
        ``rescale_grad = 1/batch`` here keeps averaging exact over the
        survivors."""
        if not self._kv_initialized:
            self._init_kvstore()
        return self._kvstore.live_workers if self._kvstore is not None \
            else 1

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None \
            else self._optimizer.lr_scheduler(self._optimizer.num_update)

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce gradients then apply optimizer (reference
        `trainer.py:258`).

        Graceful degradation: with ``MXTPU_MAX_BAD_STEPS`` > 0 a step
        whose gradients contain NaN/Inf is SKIPPED (params and
        optimizer state untouched, `bad_steps_skipped` ticks in
        `profiler.stats()`), and only that many CONSECUTIVE bad steps
        abort the run (mxtpu/resilience.py BadStepGuard).  Default 0:
        no guard, no per-step device sync."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if _res.max_bad_steps() > 0:
            # check BEFORE the allreduce: with update_on_kvstore the
            # push itself applies the update, so a post-allreduce check
            # would come too late to skip anything (and a non-finite
            # local grad makes the merged grad non-finite anyway).
            # ONE fused finiteness+norm program over the whole grad
            # tree (mx.health) replaces the old per-array sync loop.
            if self._bad_step_guard is None:
                self._bad_step_guard = _res.BadStepGuard(site="trainer")
            finite, gnorm = _health.grad_check(self._grad_vals())
            if not finite:
                # provenance first (the blame record + flight dump must
                # exist even if the guard aborts on this step)
                _health.on_nonfinite("trainer", gnorm=gnorm)
            if self._bad_step_guard.record(finite):
                # still a wall step: the telemetry stream records it as
                # skipped — with the grad norm and step id, so a burst
                # is diagnosable post-hoc from the flight recorder
                _tel.record_step(batch_size=batch_size, skipped=True,
                                 site="trainer", grad_norm=gnorm)
                return  # skip allreduce + update entirely
            _health.observe_grad_norm(gnorm)
        else:
            # guard off: deferred no-stall grad monitoring on the
            # MXTPU_HEALTH_CHECK_EVERY cadence
            _health.monitor_grads("trainer", self._grad_vals)
        # causal tracing (mx.tracing): head-sample this step; when
        # sampled, the ambient context makes the perf phase hooks and
        # the kvstore wire layer attach child spans (step ->
        # collective/optimizer -> kvstore round -> server apply).
        # step_trace() is one float compare when MXTPU_TRACE_SAMPLE=0.
        trc = _tracing.step_trace()
        if trc is not None:
            _tracing.set_current(trc)
            st0 = _time.perf_counter()
        # perf phase attribution (mx.perf): the two host-side segments
        # of a trainer step outside the compiled forward/backward —
        # gradient allreduce (collective) and the parameter update
        # (optimizer).  begin() is None when MXTPU_PERF=0.
        try:
            pt0 = _perf.begin()
            self._allreduce_grads()
            if self._kvstore is not None:
                _perf.note_phase_since("collective", pt0)
            # opt-in per-layer grad/param-norm streaming (before the
            # update so |Δw|/|w| pairs this step's grads with its
            # pre-step params)
            _health.maybe_stream_stats(
                self._stats_triple, site="trainer",
                scale=abs(self.learning_rate
                          * self._optimizer.rescale_grad))
            pt0 = _perf.begin()
            self._update(ignore_stale_grad)
            _perf.note_phase_since("optimizer", pt0)
        finally:
            if trc is not None:
                _tracing.set_current(None)
                _tracing.record_span(
                    trc, "step", _time.perf_counter() - st0, root=True,
                    step=_tel.current_step())
        _tel.record_step(batch_size=batch_size, site="trainer")
        self._num_steps += 1
        # mx.checkpoint step-boundary hook: periodic async fleet
        # snapshots and the SIGTERM checkpoint-then-drain flush both
        # fire HERE, at a consistent round boundary (one global read
        # when nothing is armed)
        if _ckpt.active():
            _ckpt.on_boundary(self._num_steps)

    @property
    def step_count(self):
        """Optimizer steps applied by this Trainer (checkpointed and
        restored by `mx.checkpoint` for deterministic re-entry)."""
        return self._num_steps

    def _grad_vals(self):
        vals = []
        for param in self._params:
            if param.grad_req != "null" and param._data is not None:
                vals.extend(g._data for g in param.list_grad())
        return vals

    def _stats_triple(self):
        """(names, param vals, grad vals) for health stat streaming
        (first device replica — the others hold the same values)."""
        names, ps, gs = [], [], []
        for param in self._params:
            if param.grad_req != "null" and param._data is not None:
                names.append(param.name)
                ps.append(param.list_data()[0]._data)
                gs.append(param.list_grad()[0]._data)
        return names, ps, gs

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore.push(i, param.list_grad(), priority=-i)
                if not self._update_on_kvstore:
                    self._kvstore.pull(i, param.list_grad(), priority=-i,
                                       ignore_sparse=False)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError("update() not supported with "
                             "update_on_kvstore=True; call step()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._zero1 is not None:
            triples = []
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                if param._data is None:
                    if not ignore_stale_grad:
                        raise MXNetError(
                            "Parameter %s has not been initialized"
                            % param.name)
                    continue
                triples.append((i, param.list_grad(), param.list_data()))
            self._zero1.update_replicas(
                triples, pre_reduced=self._kvstore is not None)
            return
        pending: Dict[int, list] = {}
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "Parameter %s has not been initialized" % param.name)
                continue
            if self._update_on_kvstore and self._kvstore is not None:
                self._kvstore.pull(i, param.list_data(), priority=-i)
                continue
            # one updater per device replica: optimizer state (momentum,
            # Adam m/v, step count) must not be shared across copies
            # (reference keeps one updater per device too)
            n_dev = len(param.list_data())
            while len(self._updaters) < n_dev:
                self._updaters.append(
                    opt_mod.get_updater(self._optimizer))
            for k, (arr, grad) in enumerate(zip(param.list_data(),
                                                param.list_grad())):
                pending.setdefault(k, []).append((i, grad, arr))
        # apply queued updates, one fused call per device replica
        # (whole-tree update: a single XLA executable updates every
        # weight/state — the TPU answer to per-param kernel dispatch)
        for k, triples in pending.items():
            self._updaters[k].update_multi(triples)

    def save_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        upd = self._zero1 if self._zero1 is not None else self._updaters[0]
        with _res.atomic_write(fname) as f:
            f.write(upd.get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        if self._zero1 is not None:
            # re-shards under the active plan (replica count may differ
            # from the saver's)
            self._zero1.set_states(states)
            return
        for upd in self._updaters:
            upd.set_states(states)
            upd.optimizer = self._optimizer
