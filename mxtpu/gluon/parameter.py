"""Parameter & ParameterDict (reference: `python/mxnet/gluon/parameter.py`).

Same deferred-init lifecycle as the reference (`parameter.py:43`): shape
may be partially unknown at construction (0 entries); `initialize()` defers
until the first forward infers the full shape.  Data lives as one NDArray
per context (single device by default; `reset_ctx`/multi-device replication
handled by the Trainer/kvstore layer).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, OrderedDict as TOrderedDict
from collections import OrderedDict

import numpy as np

from ..base import MXNetError, np_dtype
from ..context import Context, current_context, cpu
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from .. import initializer as _init_mod

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is known (reference
    `parameter.py:36`)."""


class Parameter(object):
    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data: Optional[List[NDArray]] = None
        self._grad: Optional[List[NDArray]] = None
        self._ctx_list: Optional[List[Context]] = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if isinstance(shape, int) is False and \
            shape is not None else ((shape,) if isinstance(shape, int) else None)
        self.dtype = np_dtype(dtype) if dtype is not None else None
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req if differentiable else "null"
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._stype = stype
        self._grad_stype = grad_stype

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self._shape,
                                                      self.dtype)

    # -- shape ------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(s1 == s2 or s1 in (0, -1)
                         for s1, s2 in zip(self._shape, new_shape)) \
            and len(self._shape) == len(new_shape)
        if not unknown_ok:
            raise MXNetError(
                "cannot update shape of %s from %s to %s"
                % (self.name, self._shape, new_shape))
        self._shape = tuple(new_shape)

    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # -- init lifecycle ---------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or _init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                "cannot initialize Parameter %s because it has invalid "
                "shape %s; set allow_deferred_init=True or specify in_units/"
                "in_channels" % (self.name, self._shape))
        self._finish_init(init, ctx, default_init)

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init = self._deferred_init
        if not self._shape_known():
            raise DeferredInitializationError(
                "Parameter %s has unknown shape %s after first forward"
                % (self.name, self._shape))
        self._deferred_init = ()
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        explicit = init if init is not None else self.init
        data = nd_zeros(self._shape, ctx=ctx[0],
                        dtype=self.dtype or np.float32)
        if explicit is not None:
            # an explicitly-chosen initializer wins over the name-suffix
            # dispatch (reference passes it via the '__init__' attr hint,
            # parameter.py:283) — bias_initializer='ones' must give ones
            e = _init_mod.create(explicit) if isinstance(explicit, str) \
                else explicit
            if isinstance(e, _init_mod.Initializer):
                e._init_weight(_init_mod.InitDesc(self.name), data)
            else:
                e(_init_mod.InitDesc(self.name), data)
        else:
            default = _init_mod.create(default_init) \
                if isinstance(default_init, str) else default_init
            default(_init_mod.InitDesc(self.name), data)
        self._init_impl(data, ctx)

    def _init_impl(self, data: NDArray, ctx_list: List[Context]):
        self._data = [data if c == data.ctx else data.as_in_context(c)
                      for c in ctx_list]
        self._ctx_list = list(ctx_list)
        self._init_grad()

    def _init_grad(self):
        if self.grad_req == "null":
            self._grad = None
            return
        self._grad = []
        for d in self._data:
            d.attach_grad(self.grad_req, stype=self._grad_stype)
            self._grad.append(d.grad)

    # -- access -----------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter %s not initialized yet: first forward has not "
                    "run" % self.name)
            raise MXNetError(
                "Parameter %s has not been initialized; call .initialize()"
                % self.name)

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized(ctx)
        if ctx is None:
            return self._data[0]
        for d in self._data:
            if d.ctx == ctx:
                return d
        raise MXNetError("Parameter %s not initialized on %s" % (self.name,
                                                                 ctx))

    def list_data(self) -> List[NDArray]:
        self._check_initialized()
        return list(self._data)

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized(ctx)
        if self._grad is None:
            raise MXNetError(
                "Parameter %s has grad_req='null'; no gradient" % self.name)
        if ctx is None:
            return self._grad[0]
        for d, g in zip(self._data, self._grad):
            if d.ctx == ctx:
                return g
        raise MXNetError("no grad on ctx %s" % ctx)

    def list_grad(self) -> List[NDArray]:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError("Parameter %s has grad_req='null'" % self.name)
        return list(self._grad)

    def list_ctx(self) -> List[Context]:
        if self._data is None and self._deferred_init:
            return list(self._deferred_init[1])
        self._check_initialized()
        return [d.ctx for d in self._data]

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad:
            g._set_jax((g * 0)._data)

    def set_data(self, data):
        self.shape = tuple(data.shape)
        if self._data is None:
            if self._deferred_init:
                # keep for deferred finish
                init, ctx, default_init = self._deferred_init
                self._deferred_init = ()
                src = data if isinstance(data, NDArray) else \
                    NDArray(np.asarray(data))
                self._init_impl(src.astype(self.dtype or src.dtype),
                                ctx)
                return
            raise MXNetError("Parameter %s not initialized" % self.name)
        for d in self._data:
            src = data if isinstance(data, NDArray) else NDArray(
                np.asarray(data), ctx=d.ctx)
            src = src.astype(d.dtype) if src.dtype != d.dtype else src
            d._set_jax(src.as_in_context(d.ctx)._data)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = self._data[0]
            self._init_impl(data.as_in_context(ctx[0]), ctx)
        elif self._deferred_init:
            init, _, default_init = self._deferred_init
            self._deferred_init = (init, ctx, default_init)

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is None:
            return
        with_grad = self._grad is not None
        self._data = [d.astype(self.dtype) for d in self._data]
        if with_grad:
            self._init_grad()

    def var(self):
        from ..symbol.symbol import Variable

        if self._var is None:
            self._var = Variable(self.name, shape=self._shape
                                 if self._shape_known() else None,
                                 dtype=self.dtype)
            if self.grad_req == "null" and (
                    self.name.endswith("running_mean") or
                    self.name.endswith("running_var") or
                    self.name.endswith("moving_mean") or
                    self.name.endswith("moving_var")):
                self._var._outputs[0][0].is_aux = True
        return self._var


class Constant(Parameter):
    """Non-learnable constant parameter (reference Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(np.asarray(value, dtype=np.float32))
        self.value = value

        class _CInit(_init_mod.Initializer):
            def _init_weight(s, _, arr):
                _init_mod.Initializer._set(arr, value.asnumpy())

            _init_default = _init_weight

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit())


class ParameterDict(object):
    """Prefixed dictionary of Parameters (reference `parameter.py:632`)."""

    def __init__(self, prefix="", shared: Optional["ParameterDict"] = None):
        self._prefix = prefix
        self._params: "TOrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    def __repr__(self):
        return "ParameterDict %s(%s)" % (
            self._prefix, ", ".join(self._params))

    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs) -> Parameter:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge partial shapes; positive dims must agree
                        v = tuple(v) if not isinstance(v, int) else (v,)
                        if len(v) == len(existing):
                            if any(a > 0 and b > 0 and a != b
                                   for a, b in zip(existing, v)):
                                raise MXNetError(
                                    "Parameter %r already has shape %s, "
                                    "inconsistent with requested %s"
                                    % (name, existing, v))
                            merged = tuple(
                                a if a > 0 else b
                                for a, b in zip(existing, v))
                            param._shape = merged
                        else:  # rank mismatch is inconsistent regardless
                            # of unknown dims
                            raise MXNetError(
                                "Parameter %r already has shape %s, "
                                "inconsistent with requested %s"
                                % (name, existing, v))
                        continue
                    if k in ("dtype", "init", "grad_req") and \
                            existing != v and v is not None:
                        raise MXNetError(
                            "Parameter %r already has %s=%r, inconsistent "
                            "with requested %r" % (name, k, existing, v))
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError("no constant %r and no value given" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other: "ParameterDict"):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter %r" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = _init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save

        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise MXNetError("prefix %r not in param name %r"
                                 % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import load as nd_load

        arg_dict = nd_load(filename)
        arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError("parameter %r missing in file" % name)
        for name, val in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError("parameter %r in file not in dict"
                                     % name)
                continue
            self._params[name].set_data(val)
