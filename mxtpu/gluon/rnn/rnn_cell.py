"""Recurrent cells (reference: `python/mxnet/gluon/rnn/rnn_cell.py`):
RNNCell, LSTMCell, GRUCell, SequentialRNNCell, DropoutCell, ResidualCell,
ZoneoutCell, BidirectionalCell + unroll.
"""
from __future__ import annotations

from typing import List, Optional

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ResidualCell",
           "ZoneoutCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, F=None):
    from ... import ndarray as _nd
    from ... import symbol as _sym
    from ...ndarray.ndarray import NDArray

    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        in_list = list(inputs)
        F = _nd if isinstance(in_list[0], NDArray) else _sym
        if merge is True:
            inputs = F.stack(*in_list, axis=axis)
        else:
            return in_list, axis, F, len(in_list)
        return inputs, axis, F, len(in_list)
    F = _nd if isinstance(inputs, NDArray) else _sym
    if merge is False:
        seq_len = length if length else inputs.shape[axis]
        parts = F.SliceChannel(inputs, num_outputs=seq_len, axis=axis,
                               squeeze_axis=True)
        if not isinstance(parts, list):
            parts = [parts]
        return parts, axis, F, seq_len
    return inputs, axis, F, (length or (inputs.shape[axis]
                                        if hasattr(inputs, "shape") else 0))


class RecurrentCell(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if hasattr(cell, "reset"):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as _nd

        func = func or _nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info or {})
            info.pop("__layout__", None)
            info.update(kwargs)
            states.append(func(**info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Static unroll (reference `rnn_cell.py` unroll)."""
        self.reset()
        inputs_list, axis, F, _ = _format_sequence(length, inputs, layout,
                                                   False)
        batch = inputs_list[0].shape[layout.find("N") if
                                     layout.find("N") < axis else 0] \
            if hasattr(inputs_list[0], "shape") else 0
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        outputs = []
        for i in range(length):
            output, states = self(inputs_list[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def forward(self, inputs, states):
        raise NotImplementedError


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_meta = None
        self._flags = []

    def forward(self, inputs, states):
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h, prev_c = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.Activation(slices[2], act_type="tanh")
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * prev_c + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0] if isinstance(states, (list, tuple)) else states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        nxt = F.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1.0 - update) * nxt + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ResidualCell(ModifierCell):
    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        if self.zoneout_outputs > 0:
            mask = F.Dropout(F.ones_like(next_output),
                             p=self.zoneout_outputs)
            prev = self._prev_output if self._prev_output is not None \
                else F.zeros_like(next_output)
            next_output = F.where(mask, next_output, prev)
        self._prev_output = next_output
        if self.zoneout_states > 0:
            out_states = []
            for new_s, old_s in zip(next_states, states):
                mask = F.Dropout(F.ones_like(new_s), p=self.zoneout_states)
                out_states.append(F.where(mask, new_s, old_s))
            next_states = out_states
        return next_output, next_states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as _nd

        self.reset()
        inputs_list, axis, F, _ = _format_sequence(length, inputs, layout,
                                                   False)
        states = begin_state if begin_state is not None else \
            self.begin_state(inputs_list[0].shape[0])
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, inputs_list,
                                        states[:n_l], layout, False)
        r_out, r_states = r_cell.unroll(length, list(reversed(inputs_list)),
                                        states[n_l:], layout, False)
        outputs = [F.concat(lo, ro, dim=1)
                   for lo, ro in zip(l_out, reversed(r_out))]
        return outputs, l_states + r_states
