"""Fused recurrent layers RNN/LSTM/GRU (reference: `python/mxnet/gluon/rnn/
rnn_layer.py`).

Parameters are stored per-layer/direction ({l}{i}_i2h_weight ...) like the
reference and packed into the flat cuDNN-layout vector consumed by the
fused `RNN` op (lax.scan recurrence + batched MXU input projections).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...base import MXNetError
from ..block import HybridBlock
from ...ops.rnn_op import _GATES

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError("invalid layout %r; must be TNC or NTC" % layout)
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][:self._dir]:
                    self._register_param(
                        "{}{}_i2h_weight".format(j, i),
                        shape=(ng * nh, ni), init=i2h_weight_initializer)
                    self._register_param(
                        "{}{}_h2h_weight".format(j, i),
                        shape=(ng * nh, nh), init=h2h_weight_initializer)
                    self._register_param(
                        "{}{}_i2h_bias".format(j, i),
                        shape=(ng * nh,), init=i2h_bias_initializer)
                    self._register_param(
                        "{}{}_h2h_bias".format(j, i),
                        shape=(ng * nh,), init=h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def _collect_ordered_params(self, F):
        """Weights then biases, layer-major, direction-minor — the cuDNN
        flat layout the RNN op unpacks."""
        get = (lambda p: p.var()) if F.__name__.endswith("symbol") else \
            (lambda p: p.data())
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(get(getattr(self, "{}{}_i2h_weight".format(j, i))))
                ws.append(get(getattr(self, "{}{}_h2h_weight".format(j, i))))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(get(getattr(self, "{}{}_i2h_bias".format(j, i))))
                bs.append(get(getattr(self, "{}{}_h2h_bias".format(j, i))))
        return ws + bs

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as _nd

        func = func or _nd.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            info.pop("__layout__", None)
            info.update(kwargs)
            states.append(func(**info))
        return states

    def __call__(self, inputs, states=None):
        if self._input_size == 0 and hasattr(inputs, "shape"):
            # deferred input size (reference rnn_layer infers it on the
            # first forward): complete the i2h weight shapes now
            ni = inputs.shape[self._layout.find("C")]
            if ni:
                self._input_size = ni
                self._finish_shape(ni)
        if states is None:
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch, ctx=inputs.ctx)
            skip_states = True
        else:
            if hasattr(states, "shape"):
                states = [states]
            skip_states = False
        out = super().__call__(inputs, states)
        outputs, new_states = out
        if skip_states:
            return outputs
        return outputs, new_states

    def forward(self, inputs, states):
        return super().forward(inputs, states)

    def hybrid_forward(self, F, inputs, states, **kwargs):
        if self._layout == "NTC":
            inputs = F.SwapAxis(inputs, dim1=0, dim2=1)
        # infer input_size on first call if deferred
        params = self._collect_ordered_params(F)
        flat = F._rnn_param_concat(*params, dim=0)
        rnn_args = [inputs, flat] + list(states)
        out = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        if self._mode == "lstm":
            outputs, h, c = out
            new_states = [h, c]
        else:
            outputs, h = out
            new_states = [h]
        if self._layout == "NTC":
            outputs = F.SwapAxis(outputs, dim1=0, dim2=1)
        return outputs, new_states

    def _finish_shape(self, input_size):
        ng, nh = self._gates, self._hidden_size
        ni = input_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, "{}{}_i2h_weight".format(j, i)).shape = \
                    (ng * nh, ni)
            ni = nh * self._dir


class RNN(_RNNLayer):
    """Elman RNN with tanh/relu (reference rnn_layer.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
