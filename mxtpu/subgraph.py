"""Subgraph partitioning: pluggable graph-rewrite passes over the Symbol IR.

TPU-native re-design of the reference's subgraph framework
(`src/operator/subgraph/subgraph_property.h:93` SubgraphProperty /
SubgraphSelector, `src/operator/subgraph/partition_graph.cc:735-763`
BuildSubgraph) — the extension point behind the reference's MKLDNN
fusion, TensorRT, and INT8 graph rewrites.  The reference walks the
NNVM graph in C++ selecting convex node sets and replaces each with a
node holding the subgraph; backends register under a name and
`MXNET_SUBGRAPH_BACKEND` applies one at bind time.

Here the same contract runs over `mxtpu`'s host-side Symbol DAG:

  * ``SubgraphSelector`` grows a candidate region from a seed node
    (``select`` / ``select_input`` / ``select_output`` — the reference
    selector interface verbatim in spirit);
  * ``SubgraphProperty`` turns an accepted region into a replacement
    graph (``create_subgraph_node``) and may transform parameters
    (``transform_params`` — how BN folding rewrites conv weights);
  * ``partition`` drives selection with a convexity check (contracting
    a region must not create a cycle) and rebuilds the graph;
  * backends register by name (`register_backend`) and
    ``MXTPU_SUBGRAPH_BACKEND`` applies parameter-free backends at bind
    time, mirroring ``MXNET_SUBGRAPH_BACKEND``.

What changes TPU-side is what the passes are FOR: XLA already fuses
elementwise chains into matmuls/convs, so the built-in backend does the
rewrites XLA cannot do itself — folding inference BatchNorm into the
preceding convolution's weights (backend ``"TPU"``), and the INT8
calibration rewrite (`mxtpu.contrib.quantization`) rides the same
framework with single-node regions.

The generic replacement wraps a region into a ``_subgraph_exec`` node
whose attribute carries the subgraph as JSON; its emitter re-lowers the
subgraph inline during whole-graph tracing, so a wrapped region still
compiles into the SAME fused XLA module (the reference executes
subgraph nodes through a nested executor — here the compiler inlines).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from .ops.registry import get_op, register
from .symbol.symbol import Symbol, SymbolNode, _topo_order

__all__ = ["SubgraphSelector", "SubgraphProperty", "register_backend",
           "get_backend", "list_backends", "partition",
           "partition_with_property", "ConvBNFoldProperty"]


# ---------------------------------------------------------------------------
# Selector / property interfaces (reference subgraph_property.h)
# ---------------------------------------------------------------------------

class SubgraphSelector(object):
    """Grows one candidate region.  A fresh selector is created per seed
    (reference `SubgraphProperty::CreateSubgraphSelector`)."""

    def select(self, node: SymbolNode) -> bool:
        """Is `node` a seed for a new region?"""
        return False

    def select_input(self, node: SymbolNode, input_node: SymbolNode) -> bool:
        """May `input_node` (a producer feeding `node`, already in the
        region) join the region?"""
        return False

    def select_output(self, node: SymbolNode, output_node: SymbolNode) -> bool:
        """May `output_node` (a consumer of region node `node`) join?"""
        return False

    def filter(self, candidates: List[SymbolNode]) -> List[SymbolNode]:
        """Final say over the grown region (topo order). Return a subset
        (possibly empty to reject)."""
        return candidates


class SubgraphProperty(object):
    """One named graph-rewrite backend.

    Subclasses override `create_selector` and optionally
    `create_subgraph_node` / `filter_region` / `transform_params`.
    """

    #: whether `partition` must be given parameter dicts (passes that
    #: rewrite parameter VALUES, e.g. BN folding). Parameter-free
    #: backends are eligible for the MXTPU_SUBGRAPH_BACKEND bind hook.
    needs_params = False

    def create_selector(self) -> SubgraphSelector:
        raise NotImplementedError

    def filter_region(self, region: List[SymbolNode],
                      consumers: Dict[int, List[Tuple[SymbolNode, int]]],
                      head_ids: set) -> List[SymbolNode]:
        """Structural veto with graph context (consumer map, head set).
        Runs after the selector's own `filter`."""
        return region

    def create_subgraph_node(self, sub_sym: Symbol,
                             region: List[SymbolNode],
                             input_names: List[str],
                             subgraph_id: int) -> Optional[Symbol]:
        """Build the replacement graph.

        `sub_sym`'s variable inputs are placeholders named
        `input_names`; the returned Symbol must (a) produce exactly
        ``len(sub_sym._outputs)`` outputs matching the region's external
        outputs in order, and (b) reference external values ONLY through
        variables named in `input_names` (other variables become new
        graph parameters).  Return None to leave the region unchanged.

        Default: wrap into a `_subgraph_exec` node carrying the
        subgraph as JSON (reference `CreateSubgraphNode` builds a node
        whose attrs hold the packed subgraph the same way).
        """
        from .symbol.register import invoke_symbol
        from .symbol.symbol import Variable

        # the emitter binds values to subgraph variables in the
        # subgraph's list_inputs() (topo) order — which can be a
        # permutation of the region-discovery order in `input_names`
        placeholders = [Variable(n) for n in sub_sym.list_inputs()]
        n_out = len(sub_sym._outputs)
        return invoke_symbol(
            "_subgraph_exec", placeholders,
            {"subgraph_json": sub_sym.tojson(), "n_out": n_out},
            name="sg%d_%s" % (subgraph_id, self.__class__.__name__.lower()))

    def transform_params(self, applied: List[Dict[str, Any]],
                         arg_params: Dict[str, Any],
                         aux_params: Dict[str, Any]):
        """Rewrite parameter dicts for the partitioned graph. `applied`
        holds one record per replaced region: {"region": [...nodes],
        "replacement": Symbol, "id": int}. Returns (args, aux)."""
        return arg_params, aux_params


# ---------------------------------------------------------------------------
# Backend registry (reference: SubgraphPropertyRegistry +
# MXNET_SUBGRAPH_BACKEND)
# ---------------------------------------------------------------------------

_BACKENDS: Dict[str, Callable[[], SubgraphProperty]] = {}
_bind_hook_tls = threading.local()


def register_backend(name: str, factory: Callable[[], SubgraphProperty]):
    if name in _BACKENDS:
        raise MXNetError("subgraph backend %r already registered" % name)
    _BACKENDS[name] = factory


def get_backend(name: str) -> SubgraphProperty:
    try:
        return _BACKENDS[name]()
    except KeyError:
        raise MXNetError(
            "unknown subgraph backend %r (registered: %s)"
            % (name, sorted(_BACKENDS))) from None


def list_backends() -> List[str]:
    return sorted(_BACKENDS)


# ---------------------------------------------------------------------------
# The generic wrapped-subgraph executor op
# ---------------------------------------------------------------------------

import functools


@functools.lru_cache(maxsize=256)
def _parse_subgraph(js: str) -> Symbol:
    from .symbol.symbol import load_json

    return load_json(js)


@register("_subgraph_exec",
          num_outputs=lambda attrs: int(attrs.get("n_out", 1)),
          needs_rng=True, train_aware=True)
def _subgraph_exec(key, *inputs, subgraph_json="", n_out=1, is_train=False):
    """Inline-lower a packed subgraph during tracing.

    Inputs arrive in the subgraph's `list_inputs()` order.  Aux states
    inside the subgraph are read-only here (moving-stat updates are the
    outer executor's job; wrapped regions are inference/stateless by
    contract — see module docstring).
    """
    import jax

    from . import amp as _amp

    sym = _parse_subgraph(subgraph_json)
    names = sym.list_inputs()
    if len(names) != len(inputs):
        raise MXNetError("_subgraph_exec: %d inputs for %d subgraph vars"
                         % (len(inputs), len(names)))
    env: Dict[Tuple[int, int], Any] = {}
    by_name = dict(zip(names, inputs))
    rng_i = 0
    compute_dtype = _amp.get_compute_dtype()
    for node in _topo_order(sym._outputs):
        if node.is_variable:
            env[(id(node), 0)] = by_name[node.name]
            continue
        invals = [env[(id(inode), idx)] for inode, idx in node.inputs]
        if compute_dtype is not None:
            # same per-op cast the outer executor applies
            # (executor.py _build_graph_fn) — a wrapped region must not
            # silently opt out of the AMP policy
            invals = _amp.cast_op_inputs(node.op.name, invals,
                                         compute_dtype)
        attrs = dict(node.attrs)
        if node.op.train_aware:
            attrs["is_train"] = is_train
        if node.op.needs_rng:
            sub = jax.random.fold_in(key, rng_i)
            rng_i += 1
            out = node.op.fn(sub, *invals, **attrs)
        else:
            out = node.op.fn(*invals, **attrs)
        if not isinstance(out, tuple):
            out = (out,)
        for i, o in enumerate(out):
            env[(id(node), i)] = o
    outs = tuple(env[(id(n), i)] for n, i in sym._outputs)
    return outs if len(outs) > 1 else outs[0]


def _subgraph_input_names(attrs):
    return _parse_subgraph(attrs["subgraph_json"]).list_inputs()


def _subgraph_param_shapes(shapes, attrs):
    """Backward shape solving THROUGH the packed subgraph: run the sub
    symbol's own inference (which knows each inner op's param_shapes
    hook) with whatever outer shapes are known, and surface the solved
    variable shapes — so e.g. an auto-created fc weight inside a
    wrapped region still binds (reference subgraph nodes delegate
    FInferShape to the inner graph the same way)."""
    from .symbol.symbol import _infer_graph

    sub = _parse_subgraph(attrs["subgraph_json"])
    names = sub.list_inputs()
    known = {n: tuple(s) for n, s in zip(names, shapes) if s is not None}
    try:
        solved, _ = _infer_graph(sub, known, {}, partial=True)
    except Exception:
        return {}
    out = {}
    for i, n in enumerate(names):
        if shapes[i] is None and solved.get(n) is not None:
            out[i] = tuple(solved[n])
    return out


def _register_subgraph_meta():
    from .symbol.op_meta import OpMeta, register_meta

    register_meta("_subgraph_exec",
                  OpMeta(_subgraph_input_names,
                         param_shapes=_subgraph_param_shapes))


_register_subgraph_meta()


# ---------------------------------------------------------------------------
# Partition driver (reference partition_graph.cc BuildSubgraph)
# ---------------------------------------------------------------------------

def _consumer_map(nodes: Sequence[SymbolNode]):
    cons: Dict[int, List[Tuple[SymbolNode, int]]] = {}
    for n in nodes:
        if n.is_variable:
            continue
        for (src, idx) in n.inputs:
            cons.setdefault(id(src), []).append((n, idx))
    return cons


def _grow_region(seed: SymbolNode, selector: SubgraphSelector,
                 consumers, claimed: set) -> List[SymbolNode]:
    region = [seed]
    rset = {id(seed)}
    changed = True
    while changed:
        changed = False
        for n in list(region):
            for (src, _idx) in n.inputs:
                if src.is_variable or id(src) in rset or id(src) in claimed:
                    continue
                if selector.select_input(n, src):
                    region.append(src)
                    rset.add(id(src))
                    changed = True
            for (c, _idx) in consumers.get(id(n), ()):
                if id(c) in rset or id(c) in claimed:
                    continue
                if selector.select_output(n, c):
                    region.append(c)
                    rset.add(id(c))
                    changed = True
    return region


def _is_convex(region_ids: set, region: List[SymbolNode], consumers) -> bool:
    """Contracting `region` must not create a cycle: no external
    descendant of the region may feed back into it."""
    ext_desc: set = set()
    stack = []
    for n in region:
        for (c, _i) in consumers.get(id(n), ()):
            if id(c) not in region_ids:
                stack.append(c)
    while stack:
        node = stack.pop()
        if id(node) in ext_desc:
            continue
        ext_desc.add(id(node))
        for (c, _i) in consumers.get(id(node), ()):
            if id(c) not in region_ids and id(c) not in ext_desc:
                stack.append(c)
    for n in region:
        for (src, _i) in n.inputs:
            if id(src) in ext_desc:
                return False
    return True


def _entry_name(src: SymbolNode, idx: int) -> str:
    if src.is_variable:
        return src.name
    if src.num_outputs() == 1:
        return src.name + "_output"
    return "%s_output%d" % (src.name, idx)


def partition_with_property(sym: Symbol, prop: SubgraphProperty,
                            arg_params: Optional[Dict[str, Any]] = None,
                            aux_params: Optional[Dict[str, Any]] = None):
    """Apply one property to `sym`. Returns (new_sym, args, aux) when
    params were given, else new_sym."""
    nodes = sym._topo()
    consumers = _consumer_map(nodes)
    head_ids = {id(n) for n, _ in sym._outputs}
    claimed: set = set()
    regions: List[List[SymbolNode]] = []
    node_pos = {id(n): i for i, n in enumerate(nodes)}

    for node in nodes:
        if node.is_variable or id(node) in claimed:
            continue
        selector = prop.create_selector()
        if not selector.select(node):
            continue
        region = _grow_region(node, selector, consumers, claimed)
        region.sort(key=lambda n: node_pos[id(n)])
        region = selector.filter(region)
        if region:
            region = prop.filter_region(region, consumers, head_ids)
        if not region:
            continue
        rset = {id(n) for n in region}
        if not _is_convex(rset, region, consumers):
            continue
        regions.append(region)
        claimed |= rset

    if not regions:
        if arg_params is not None or aux_params is not None:
            return sym, dict(arg_params or {}), dict(aux_params or {})
        return sym

    region_of: Dict[int, int] = {}
    for ri, region in enumerate(regions):
        for n in region:
            region_of[id(n)] = ri

    # per-region external inputs / outputs (stable order)
    region_inputs: List[List[Tuple[SymbolNode, int]]] = []
    region_outputs: List[List[Tuple[SymbolNode, int]]] = []
    for ri, region in enumerate(regions):
        rset = {id(n) for n in region}
        ins: List[Tuple[SymbolNode, int]] = []
        seen_in = set()
        outs: List[Tuple[SymbolNode, int]] = []
        seen_out = set()
        for n in region:
            for e in n.inputs:
                if id(e[0]) in rset:
                    continue
                k = (id(e[0]), e[1])
                if k not in seen_in:
                    seen_in.add(k)
                    ins.append(e)
            for (c, _i) in consumers.get(id(n), ()):
                if id(c) in rset:
                    continue
                for (src, idx) in c.inputs:
                    if id(src) == id(n):
                        k = (id(src), idx)
                        if k not in seen_out:
                            seen_out.add(k)
                            outs.append((src, idx))
            if id(n) in head_ids:
                for (hn, hi) in sym._outputs:
                    if id(hn) == id(n):
                        k = (id(hn), hi)
                        if k not in seen_out:
                            seen_out.add(k)
                            outs.append((hn, hi))
        region_inputs.append(ins)
        region_outputs.append(outs)

    entry_map: Dict[Tuple[int, int], Tuple[SymbolNode, int]] = {}
    cloned: Dict[int, SymbolNode] = {}
    applied: List[Dict[str, Any]] = []
    instantiating: set = set()

    def clone_plain(node: SymbolNode) -> SymbolNode:
        if id(node) in cloned:
            return cloned[id(node)]
        if node.is_variable:
            new = SymbolNode(None, node.name, {}, [], is_aux=node.is_aux)
            new.ext_attrs = dict(node.ext_attrs)
            cloned[id(node)] = new
            return new
        new_inputs = [map_entry(e) for e in node.inputs]
        new = SymbolNode(node.op, node.name, dict(node.attrs), new_inputs)
        new.ext_attrs = dict(node.ext_attrs)
        cloned[id(node)] = new
        return new

    def map_entry(entry: Tuple[SymbolNode, int]) -> Tuple[SymbolNode, int]:
        node, idx = entry
        key = (id(node), idx)
        if key in entry_map:
            return entry_map[key]
        ri = region_of.get(id(node))
        if ri is None:
            new = clone_plain(node)
            mapped = (new, idx)
            entry_map[key] = mapped
            return mapped
        instantiate_region(ri)
        if key not in entry_map:
            raise MXNetError(
                "subgraph replacement for region %d did not produce "
                "output %s[%d]" % (ri, node.name, idx))
        return entry_map[key]

    def instantiate_region(ri: int):
        if ri in instantiating:
            raise MXNetError("cycle while instantiating subgraph region %d "
                             "(property %s broke convexity)"
                             % (ri, type(prop).__name__))
        if any((id(n), i) in entry_map
               for (n, i) in region_outputs[ri]):
            return
        instantiating.add(ri)
        region = regions[ri]
        ins = region_inputs[ri]
        outs = region_outputs[ri]
        # build the subgraph symbol over placeholder variables
        input_names = []
        ph_nodes: Dict[Tuple[int, int], SymbolNode] = {}
        used = set()
        for (src, idx) in ins:
            nm = _entry_name(src, idx)
            while nm in used:
                nm += "_"
            used.add(nm)
            input_names.append(nm)
            ph = SymbolNode(None, nm, {}, [], is_aux=src.is_aux)
            if src.is_variable:
                ph.ext_attrs = dict(src.ext_attrs)
            ph_nodes[(id(src), idx)] = ph
        sub_cloned: Dict[int, SymbolNode] = {}

        def sub_clone(entry):
            node, idx = entry
            k = (id(node), idx)
            if k in ph_nodes:
                return (ph_nodes[k], 0)
            if id(node) in sub_cloned:
                return (sub_cloned[id(node)], idx)
            new = SymbolNode(node.op, node.name, dict(node.attrs),
                             [sub_clone(e) for e in node.inputs])
            new.ext_attrs = dict(node.ext_attrs)
            sub_cloned[id(node)] = new
            return (new, idx)

        sub_sym = Symbol([sub_clone(e) for e in outs])
        replacement = prop.create_subgraph_node(sub_sym, region,
                                                input_names, ri)
        if replacement is None:
            # leave the region as-is: clone its nodes plainly
            for n in region:
                for i in range(n.num_outputs()):
                    k = (id(n), i)
                    if k not in entry_map:
                        new = clone_plain_region_node(n, ri)
                        entry_map[k] = (new, i)
            instantiating.discard(ri)
            return
        if len(replacement._outputs) != len(outs):
            raise MXNetError(
                "replacement for region %d has %d outputs, region has %d"
                % (ri, len(replacement._outputs), len(outs)))
        # graft the replacement: substitute placeholder variables with
        # the mapped external entries; other variables become new params
        ph_by_name = {nm: map_entry(e) for nm, e in zip(input_names, ins)}
        graft_memo: Dict[int, SymbolNode] = {}

        def graft(entry):
            node, idx = entry
            if node.is_variable and node.name in ph_by_name:
                return ph_by_name[node.name]
            if id(node) in graft_memo:
                return (graft_memo[id(node)], idx)
            if node.is_variable:
                new = SymbolNode(None, node.name, {}, [],
                                 is_aux=node.is_aux)
                new.ext_attrs = dict(node.ext_attrs)
            else:
                new = SymbolNode(node.op, node.name, dict(node.attrs),
                                 [graft(e) for e in node.inputs])
                new.ext_attrs = dict(node.ext_attrs)
            graft_memo[id(node)] = new
            return (new, idx)

        for (src_entry, rep_entry) in zip(outs, replacement._outputs):
            entry_map[(id(src_entry[0]), src_entry[1])] = graft(rep_entry)
        applied.append({"region": region, "replacement": replacement,
                        "id": ri, "input_names": input_names})
        instantiating.discard(ri)

    def clone_plain_region_node(node: SymbolNode, ri: int) -> SymbolNode:
        if id(node) in cloned:
            return cloned[id(node)]
        new_inputs = []
        for e in node.inputs:
            if region_of.get(id(e[0])) == ri:
                inner = clone_plain_region_node(e[0], ri)
                new_inputs.append((inner, e[1]))
            else:
                new_inputs.append(map_entry(e))
        new = SymbolNode(node.op, node.name, dict(node.attrs), new_inputs)
        new.ext_attrs = dict(node.ext_attrs)
        cloned[id(node)] = new
        return new

    new_sym = Symbol([map_entry(e) for e in sym._outputs])

    if arg_params is not None or aux_params is not None:
        args = dict(arg_params or {})
        aux = dict(aux_params or {})
        args, aux = prop.transform_params(applied, args, aux)
        keep_args = set(new_sym.list_arguments())
        keep_aux = set(new_sym.list_auxiliary_states())
        args = {k: v for k, v in args.items() if k in keep_args}
        aux = {k: v for k, v in aux.items() if k in keep_aux}
        return new_sym, args, aux
    return new_sym


def partition(sym: Symbol, backend: str,
              arg_params: Optional[Dict[str, Any]] = None,
              aux_params: Optional[Dict[str, Any]] = None):
    """Apply the named backend (reference: `partition_graph.cc` driven
    by `MXNET_SUBGRAPH_BACKEND` / `Symbol.optimize_for`)."""
    prop = get_backend(backend)
    if prop.needs_params and arg_params is None:
        raise MXNetError(
            "subgraph backend %r rewrites parameter values; call with "
            "arg_params/aux_params (e.g. sym.optimize_for(%r, args, aux))"
            % (backend, backend))
    return partition_with_property(sym, prop, arg_params, aux_params)


def apply_bind_hook(sym: Symbol) -> Symbol:
    """Bind-time hook: MXTPU_SUBGRAPH_BACKEND applies a parameter-free
    backend to every bound Symbol (reference MXNET_SUBGRAPH_BACKEND,
    `graph_executor.cc` init).  Param-rewriting backends are skipped
    with a warning — they need `Symbol.optimize_for`."""
    name = os.environ.get("MXTPU_SUBGRAPH_BACKEND", "")
    if not name:
        return sym
    if getattr(_bind_hook_tls, "active", False):
        return sym  # re-entrant bind (e.g. calibration) — already applied
    if name not in _BACKENDS:
        import logging

        logging.getLogger(__name__).warning(
            "MXTPU_SUBGRAPH_BACKEND=%r is not a registered backend %s",
            name, sorted(_BACKENDS))
        return sym
    prop = get_backend(name)
    if prop.needs_params:
        import logging

        logging.getLogger(__name__).warning(
            "MXTPU_SUBGRAPH_BACKEND=%r rewrites parameters; use "
            "Symbol.optimize_for instead — skipping bind-time partition",
            name)
        return sym
    _bind_hook_tls.active = True
    try:
        return partition_with_property(sym, prop)
    finally:
        _bind_hook_tls.active = False


# ---------------------------------------------------------------------------
# Built-in backend "TPU": fold inference BatchNorm into Convolution
# ---------------------------------------------------------------------------

class _ConvBNSelector(SubgraphSelector):
    def select(self, node):
        return (not node.is_variable) and node.op.name == "Convolution"

    def select_output(self, node, output_node):
        return (node.op.name == "Convolution"
                and not output_node.is_variable
                and output_node.op.name == "BatchNorm")


class ConvBNFoldProperty(SubgraphProperty):
    """Inference-time Conv+BN fold (the useful half of the reference's
    MKLDNN conv fusion, `src/operator/subgraph/mkldnn/mkldnn_conv.cc`):

        y = gamma * (conv(x, W) + b - mean) / sqrt(var + eps) + beta
          = conv(x, W * s) + (b - mean) * s + beta,   s = gamma / sqrt(var+eps)

    The BatchNorm node disappears; the convolution's weight/bias are
    rewritten offline by `transform_params`.  Valid only for inference
    semantics (moving statistics) — training graphs keep their BN.
    """

    needs_params = True

    def create_selector(self):
        return _ConvBNSelector()

    def filter_region(self, region, consumers, head_ids):
        if len(region) != 2:
            return []
        conv, bn = region
        if conv.op.name != "Convolution" or bn.op.name != "BatchNorm":
            return []
        # BN must consume the conv's output 0 as data
        if not bn.inputs or id(bn.inputs[0][0]) != id(conv):
            return []
        # channel axis must be the conv's feature axis
        if int(bn.attrs.get("axis", 1)) != 1:
            return []
        if bn.attrs.get("output_mean_var"):
            return []
        # the conv output must feed ONLY this BN (folding changes it)
        cons = consumers.get(id(conv), [])
        if len(cons) != 1 or id(conv) in head_ids:
            return []
        # external consumers may only use BN output 0
        for (c, _i) in consumers.get(id(bn), ()):
            for (src, idx) in c.inputs:
                if id(src) == id(bn) and idx != 0:
                    return []
        # all BN params + conv weight must be variables we can rewrite
        for (src, _i) in bn.inputs[1:]:
            if not src.is_variable:
                return []
        if len(conv.inputs) < 2 or not conv.inputs[1][0].is_variable:
            return []
        if not conv.attrs.get("no_bias", False):
            if len(conv.inputs) < 3 or not conv.inputs[2][0].is_variable:
                return []
        return region

    def create_subgraph_node(self, sub_sym, region, input_names, sid):
        from .symbol.register import invoke_symbol
        from .symbol.symbol import Variable

        conv, bn = region
        attrs = dict(conv.attrs)
        no_bias = attrs.get("no_bias", False)
        attrs["no_bias"] = False
        wname = conv.inputs[1][0].name
        bname = (conv.inputs[2][0].name if not no_bias
                 and len(conv.inputs) >= 3 else conv.name + "_folded_bias")
        data_ph = Variable(input_names[0])
        out = invoke_symbol("Convolution",
                            [data_ph, Variable(wname), Variable(bname)],
                            attrs, name=conv.name)
        return out

    def transform_params(self, applied, arg_params, aux_params):
        for rec in applied:
            conv, bn = rec["region"]
            wname = conv.inputs[1][0].name
            no_bias = conv.attrs.get("no_bias", False)
            bname = (conv.inputs[2][0].name if not no_bias
                     and len(conv.inputs) >= 3
                     else conv.name + "_folded_bias")
            gname, bename = bn.inputs[1][0].name, bn.inputs[2][0].name
            mname, vname = bn.inputs[3][0].name, bn.inputs[4][0].name
            eps = float(bn.attrs.get("eps", 1e-3))
            fix_gamma = bool(bn.attrs.get("fix_gamma", True))

            def host(d, n):
                v = d[n]
                return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

            W = host(arg_params, wname)
            mean = host(aux_params if mname in aux_params else arg_params,
                        mname)
            var = host(aux_params if vname in aux_params else arg_params,
                       vname)
            beta = host(arg_params, bename)
            gamma = (np.ones_like(beta) if fix_gamma
                     else host(arg_params, gname))
            b = (np.zeros(W.shape[0], W.dtype) if no_bias
                 else host(arg_params, bname))
            s = gamma / np.sqrt(var + eps)
            Wf = W * s.reshape((-1,) + (1,) * (W.ndim - 1))
            bf = (b - mean) * s + beta
            from .ndarray.ndarray import array as nd_array

            arg_params[wname] = nd_array(Wf.astype(W.dtype))
            arg_params[bname] = nd_array(bf.astype(W.dtype))
            for gone in (gname, bename):
                arg_params.pop(gone, None)
            for gone in (mname, vname):
                aux_params.pop(gone, None)
                arg_params.pop(gone, None)
        return arg_params, aux_params


register_backend("TPU", ConvBNFoldProperty)
