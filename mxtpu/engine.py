"""Dependency engine — python surface over the native scheduler.

Reference: `include/mxnet/engine.h:115` (PushAsync/NewVariable/
WaitForVar/WaitForAll), `src/engine/threaded_engine_perdevice.cc`
(default threaded engine), `src/engine/naive_engine.cc` (sync debug
engine selected by MXNET_ENGINE_TYPE).

On TPU the XLA/PJRT runtime orders device compute, so this engine
schedules *host-side* work: IO, decode, checkpoint writes, host
transfers.  Two implementations behind one API, chosen by
MXTPU_ENGINE_TYPE (reference MXNET_ENGINE_TYPE):

  * ``ThreadedEngine`` — the native C++ versioned-var scheduler
    (src/engine.cc via ctypes); python callables run on native worker
    threads (ctypes re-acquires the GIL per call; numpy/jax release it
    during real work).
  * ``NaiveEngine``    — synchronous in-process execution for
    deterministic debugging, like the reference's NaiveEngine.

Python exceptions raised by async fns are captured and re-raised at
``wait_for_var`` — the reference's async error story
(`threaded_engine.h:362-372`).
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence

from .base import MXNetError
from . import _native

__all__ = ["Engine", "NaiveEngine", "ThreadedEngine", "get_engine",
           "set_engine"]


class Var(object):
    __slots__ = ("handle", "_engine")

    def __init__(self, handle, engine):
        self.handle = handle
        self._engine = engine

    @property
    def version(self):
        return self._engine.var_version(self)


class Engine(object):
    """Abstract engine API."""

    def new_var(self) -> Var:
        raise NotImplementedError

    def push(self, fn: Callable[[], None], const_vars: Sequence[Var] = (),
             mutable_vars: Sequence[Var] = (), priority: int = 0):
        raise NotImplementedError

    def wait_for_var(self, var: Var):
        raise NotImplementedError

    def wait_for_all(self):
        raise NotImplementedError

    def var_version(self, var: Var) -> int:
        raise NotImplementedError

    def delete_var(self, var: Var):
        pass


class NaiveEngine(Engine):
    """Synchronous engine (reference `naive_engine.cc:50`): push
    executes immediately; errors raise at the push site but are also
    recorded for wait_for_var parity."""

    def __init__(self):
        self._versions: Dict[int, int] = {}
        self._errors: Dict[int, BaseException] = {}
        self._next = 1

    def new_var(self) -> Var:
        v = Var(self._next, self)
        self._next += 1
        self._versions[v.handle] = 0
        return v

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        try:
            fn()
        except BaseException as e:
            for v in mutable_vars:
                self._errors[v.handle] = e
            raise
        finally:
            for v in mutable_vars:
                self._versions[v.handle] = \
                    self._versions.get(v.handle, 0) + 1

    def wait_for_var(self, var: Var):
        err = self._errors.pop(var.handle, None)
        if err is not None:
            raise MXNetError(str(err)) from err

    def wait_for_all(self):
        pass

    def var_version(self, var: Var) -> int:
        return self._versions.get(var.handle, 0)


class ThreadedEngine(Engine):
    """Native threaded engine (src/engine.cc)."""

    def __init__(self, num_threads: Optional[int] = None):
        lib = _native.get_lib()
        if lib is None:
            raise MXNetError(
                "native runtime not built: run `make -C src` (or set "
                "MXTPU_NATIVE_LIB), or use MXTPU_ENGINE_TYPE=NaiveEngine")
        if num_threads is None:
            num_threads = int(os.environ.get(
                "MXTPU_CPU_WORKER_NTHREADS",
                os.environ.get("MXNET_CPU_WORKER_NTHREADS", "4")))
        self._lib = lib
        self._h = ctypes.c_void_p(lib.MXTPUEngineCreate(num_threads))
        self._cb_lock = threading.Lock()
        self._callbacks: Dict[int, tuple] = {}  # keep refs until done
        self._errors: Dict[int, BaseException] = {}  # var handle -> exc
        self._next_cb = 1

        @_native.AsyncFnType
        def trampoline(param):
            key = int(param)
            with self._cb_lock:
                fn, mvars = self._callbacks.pop(key)
            try:
                fn()
                return 0
            except BaseException as e:  # captured, surfaced at wait
                with self._cb_lock:
                    for vh in mvars:
                        self._errors[vh] = e
                return -1

        self._trampoline = trampoline  # keep alive

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.MXTPUEngineFree(self._h)
                self._h = None
        except Exception:
            pass

    def new_var(self) -> Var:
        return Var(self._lib.MXTPUEngineNewVar(self._h), self)

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        with self._cb_lock:
            key = self._next_cb
            self._next_cb += 1
            self._callbacks[key] = (fn, [v.handle for v in mutable_vars])
        cvars = (ctypes.c_uint64 * max(1, len(const_vars)))(
            *[v.handle for v in const_vars])
        mvars = (ctypes.c_uint64 * max(1, len(mutable_vars)))(
            *[v.handle for v in mutable_vars])
        rc = self._lib.MXTPUEnginePushAsync(
            self._h, self._trampoline, ctypes.c_void_p(key),
            cvars, len(const_vars), mvars, len(mutable_vars), priority)
        if rc != 0:
            raise MXNetError("PushAsync failed: %s"
                             % self._lib.MXTPUGetLastError().decode())

    def wait_for_var(self, var: Var):
        rc = self._lib.MXTPUEngineWaitForVar(self._h, var.handle)
        with self._cb_lock:
            err = self._errors.pop(var.handle, None)
        if rc != 0 or err is not None:
            raise MXNetError("async op failed: %s"
                             % (err if err is not None else rc)) \
                from err

    def wait_for_all(self):
        self._lib.MXTPUEngineWaitForAll(self._h)

    def var_version(self, var: Var) -> int:
        return int(self._lib.MXTPUEngineVarVersion(self._h, var.handle))

    def delete_var(self, var: Var):
        self._lib.MXTPUEngineDeleteVar(self._h, var.handle)

    def num_outstanding(self) -> int:
        return int(self._lib.MXTPUEngineNumOutstanding(self._h))


_engine: Optional[Engine] = None
_engine_lock = threading.Lock()


def get_engine() -> Engine:
    """Process engine singleton, selected by MXTPU_ENGINE_TYPE
    (ThreadedEngine default when the native lib is built, else Naive)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            kind = os.environ.get(
                "MXTPU_ENGINE_TYPE",
                os.environ.get("MXNET_ENGINE_TYPE", ""))
            if kind == "NaiveEngine":
                _engine = NaiveEngine()
            elif kind == "ThreadedEngine":
                _engine = ThreadedEngine()
            else:
                _engine = ThreadedEngine() if _native.available() \
                    else NaiveEngine()
        return _engine


def set_engine(engine: Engine):
    global _engine
    with _engine_lock:
        _engine = engine
