"""Server/scheduler bootstrap for distributed KVStore.

Reference: `python/mxnet/kvstore_server.py` — a process whose
MXTPU_ROLE/DMLC_ROLE is ``server`` or ``scheduler`` calls
:func:`init_module` (the reference does this at import of mxnet inside
the launched process) and blocks serving until the worker group
finishes.  Launched by `tools/launch.py`.
"""
from __future__ import annotations

import os

from . import _ps

__all__ = ["KVStoreServer", "init_module"]


class KVStoreServer(object):
    def __init__(self):
        self._role = _ps.role_from_env()

    def run(self, controller=None):
        """controller: optional fn(head, body) receiving app-level
        commands sent via send_command_to_servers (heads other than the
        built-in set_optimizer) — the reference MXKVStoreRunServer
        controller semantics."""
        if self._role == "scheduler":
            _ps.run_scheduler()
        elif self._role == "server":
            _ps.run_server(controller=controller)
        else:
            raise RuntimeError("KVStoreServer started with role %r"
                               % self._role)


def init_module():
    """If this process is a server/scheduler, serve and exit (mirrors the
    reference's blocking server loop)."""
    role = _ps.role_from_env()
    if role in ("server", "scheduler"):
        KVStoreServer().run()
        # clean shutdown reached: disarm the flight recorder FIRST —
        # the launcher's routine teardown SIGTERM races this exit, and
        # a healthy run must not leave crash-style flight corpses —
        # then flush the final snapshot explicitly (the hard exit
        # below skips atexit)
        import signal

        from . import obs, telemetry

        telemetry.uninstall_flight_recorder()
        # the launcher's routine teardown SIGTERM races this epilogue
        # (it fires the instant the workers exit — exactly when a
        # healthy scheduler reaches here, with the flight recorder
        # just disarmed): mask it for the few ms the final ledger
        # rows + snapshot take.  The launcher escalates to SIGKILL
        # after 10s, so a wedged epilogue still cannot leak the role.
        try:
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
        except ValueError:
            pass
        # the hard exit below skips atexit: close the obs plane
        # explicitly so the server/scheduler's final sample + ledger
        # summary row land like every other role's
        obs.stop()
        telemetry.flush()
        # hard exit, ps-lite style: the role's work is DONE when run()
        # returns, but interpreter/native teardown with live daemon
        # threads (XLA/PJRT pools used by the server-side updater) can
        # abort ("terminate called without an active exception"),
        # turning a clean shutdown into a nonzero exit that the
        # failure-honest launcher would flag
        os._exit(0)
