"""Automatic mixed precision (AMP) — the TPU bfloat16 compute policy.

Reference analog: `python/mxnet/contrib/amp/` (v1.5 AMP with
FP16_FUNCS/FP32_FUNCS op lists and cast insertion).  The TPU-native
redesign keeps parameters in float32 (master weights) and casts
per-op INSIDE the single fused XLA module built by
`executor._build_graph_fn`:

  * matmul/conv FLOPs ops run in the compute dtype (bfloat16 hits the
    MXU at full rate),
  * numerically-sensitive ops (softmax/losses/norm-stats) are upcast
    to float32,
  * everything else runs in whatever dtype arrives (XLA fuses the
    casts into neighboring kernels).

Because the cast happens inside the traced graph, gradients flow
through the cast's vjp and arrive as float32 — the optimizer needs no
`multi_precision` handling and the fused whole-tree update still
applies.

Usage::

    mxtpu.amp.set_compute_dtype("bfloat16")   # before bind/hybridize
    ... bind / fit ...
    mxtpu.amp.set_compute_dtype(None)         # back to pure fp32

The policy is captured at graph-BUILD time (bind / first hybridized
call), matching the reference where `amp.init()` must run before the
model is created.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

__all__ = ["set_compute_dtype", "get_compute_dtype", "scope",
           "LOWP_OPS", "FP32_OPS"]

_state = threading.local()

# The FLOPs/bandwidth carriers: run these in the low-precision compute
# dtype (reference FP16_FUNCS list, `contrib/amp/lists/symbol.py`).
# Everything NOT in either list runs in whatever dtype arrives and
# multi-input ops promote to the widest input via jnp's promotion —
# the reference's FP16_FP32_FUNCS + WIDEST_TYPE_CASTS behavior for
# free, so activations stay bf16 across elementwise/activation chains.
LOWP_OPS = {
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "RNN", "Correlation", "_linalg_gemm", "_linalg_gemm2",
    # bandwidth-bound stages: keeping them bf16 halves their HBM traffic
    "Pooling", "Pooling_v1", "_contrib_AdaptiveAvgPooling2D",
    "UpSampling", "_contrib_BilinearResize2D", "BilinearSampler",
    "Embedding", "Concat", "add_n",
}

# Numerically sensitive: force float32 inputs (reference FP32_FUNCS).
FP32_OPS = {
    "SoftmaxOutput", "softmax", "log_softmax", "SoftmaxActivation",
    "LayerNorm", "InstanceNorm", "L2Normalization", "LRN",
    "CTCLoss", "_contrib_CTCLoss", "MakeLoss", "SVMOutput",
    "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput", "norm", "exp", "log", "log2", "log10",
    "expm1", "log1p", "pow", "_power", "_power_scalar", "erfinv",
    "SpatialTransformer", "GridGenerator",
}


def set_compute_dtype(dtype: Optional[str]) -> None:
    """Set (or clear, with None) the AMP compute dtype for graphs built
    after this call."""
    _state.dtype = dtype


def get_compute_dtype() -> Optional[str]:
    return getattr(_state, "dtype", None)


@contextmanager
def scope(dtype: Optional[str]):
    prev = get_compute_dtype()
    set_compute_dtype(dtype)
    try:
        yield
    finally:
        set_compute_dtype(prev)


# inputs that must NEVER be narrowed even inside a LOWP op: bf16's
# 8-bit mantissa rounds float-typed INDEX tensors (the MXNet convention
# stores indices as float32) above 256 to the wrong integer
_LOWP_SKIP_INPUTS = {"Embedding": {0}}


def cast_op_inputs(op_name: str, invals, dtype):
    """Apply the policy to one node's inputs (float arrays only — int
    index/label-ish inputs pass through untouched)."""
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    f32 = jnp.float32
    if op_name in LOWP_OPS:
        skip = _LOWP_SKIP_INPUTS.get(op_name, ())
        return [v.astype(dt)
                if i not in skip and getattr(v, "dtype", None) == f32
                else v
                for i, v in enumerate(invals)]
    if op_name in FP32_OPS:
        return [v.astype(f32)
                if getattr(v, "dtype", None) == dt else v
                for v in invals]
    return invals
