"""End-to-end causal tracing: cross-process spans with critical-path
and tail-latency attribution.

Every observability plane so far answers "what is each process doing"
— gauges (`mx.telemetry`), phases (`mx.perf`), live scrapes
(`mx.obs`).  This module answers "where did THIS p99 request or THIS
slow training round spend its time" across process boundaries:

  * **Causal context** — a W3C-``traceparent``-style :class:`Context`
    (32-hex trace id, 16-hex span id, sampled flag) propagated over
    BOTH wire protocols: the serve HTTP path (`mx.serve.Client` stamps
    the ``traceparent`` header; the frontend/batcher/dispatch continue
    the trace) and the PS socket protocol (push/pull messages carry a
    ``trace`` field into the server apply and chain-replication
    spans).  In-process, the gluon Trainer opens a per-step span tree
    (step → collective/optimizer/kvstore round) and parks the context
    in a thread-local (:func:`current`) so the kvstore and `mx.perf`
    phase hooks attach children without signature churn.

  * **Spans on the telemetry ring** — each finished span is ONE
    ``span`` record (:data:`telemetry.EVENT_KINDS`): trace/span/parent
    ids, a name from the `mx.perf` phase vocabulary where one applies
    (so spans and phase gauges reconcile), duration, and the existing
    step/round correlation ids.  Spans ride the per-role telemetry
    files; ``telemetry.merge_dir`` calls :func:`stitch` to join them
    into chrome-trace flow events by trace id and a ``tracing`` rollup
    in cluster.json.

  * **Sampling** — head-based: :func:`start_request` /
    :func:`step_trace` flip a deterministic per-process RNG
    (``MXTPU_TRACE_SAMPLE``, default 0.01; ``MXTPU_TRACE_SEED`` pins
    the decision sequence).  The tail-latency escape hatch is
    RETRO-KEEP: an unsampled request still carries an (unsampled)
    context, the completion site measures its wall, and anything over
    the rolling per-window p95 (:func:`slow_keep`, fed by the
    histogram the site already records into) gets its spans emitted
    after the fact — p99s are always attributable even at a 1%% head
    rate.  ``MXTPU_TRACE_SAMPLE=0`` (or ``MXTPU_TRACE=0``) reduces
    every hook to one bool check (<10us/step budget, asserted by
    ``tools/check_trace.py``).

  * **Critical path** — :func:`critical_path` walks one stitched span
    tree and attributes each span's SELF time (duration minus direct
    children) to its segment name, yielding the dominant chain, e.g.
    ``queue_wait 41% -> batch_linger 22% -> device 30%``
    (``tools/trace_path.py`` is the CLI).  Per-role dominant segments
    flow through the registered ``tracing`` metrics provider into
    heartbeats, ``/snapshot.json`` and ``cluster_live.json`` (the
    `tools/dash.py` crit-path column).

See `docs/observability.md` §Tracing.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .base import getenv, getenv_bool

__all__ = [
    "Context",
    "enabled",
    "sample_rate",
    "set_sample_rate",
    "seed",
    "start_request",
    "step_trace",
    "parse",
    "current",
    "set_current",
    "use",
    "record_span",
    "finish_request",
    "slow_keep",
    "note_exemplar",
    "exemplar",
    "critical_path",
    "stitch",
    "metrics_block",
    "reset",
]

_ENABLED = getenv_bool("MXTPU_TRACE", True)


def _env_rate() -> float:
    try:
        return float(getenv("MXTPU_TRACE_SAMPLE", "0.01") or 0.01)
    except ValueError:
        return 0.01


_RATE = _env_rate() if _ENABLED else 0.0

# deterministic sampling under a fixed seed (tests / reproducing a
# sampled run); unset = OS entropy
_seed_env = getenv("MXTPU_TRACE_SEED")
_rng = random.Random(int(_seed_env)) if _seed_env else random.Random()
# id generation is SEPARATE from the sampling decision stream so a
# fixed seed pins which calls sample without making every process
# mint the same trace ids
_idrng = random.Random(os.urandom(8))

_lock = threading.Lock()
_tls = threading.local()

# per-role segment accumulators (name -> [count, sum_s, first_ts]) —
# the metrics-provider / dash substrate
_SEG: Dict[str, List[float]] = {}
# counters mirrored into profiler.stats() too; kept here for the
# metrics block so a heartbeat never needs the profiler
_COUNTS = {"sampled": 0, "retro_kept": 0, "spans": 0}

# slowest-kept-request exemplars per histogram name:
# name -> {"trace_id", "value", "ts"} (the OpenMetrics exemplar store)
_EXEMPLAR: Dict[str, Dict[str, Any]] = {}
_EXEMPLAR_WINDOW_S = 60.0

# rolling-p95 state per histogram name for retro-keep:
# name -> [hist_state, p95_or_None, last_refresh_monotonic]
_P95: Dict[str, list] = {}
_P95_REFRESH_S = 2.0


def enabled() -> bool:
    """Tracing armed?  ``MXTPU_TRACE=0`` or ``MXTPU_TRACE_SAMPLE=0``
    reduces every producer hook to one bool/float check."""
    return _ENABLED and _RATE > 0.0


def sample_rate() -> float:
    return _RATE


def set_sample_rate(rate: float) -> None:
    """Flip the head-sampling rate at runtime (tests / check tools)."""
    global _RATE
    _RATE = max(0.0, min(1.0, float(rate)))


def seed(n: int) -> None:
    """Pin the sampling-decision stream (``MXTPU_TRACE_SEED``
    equivalent): after ``seed(n)``, the sampled/unsampled sequence of
    :func:`start_request` / :func:`step_trace` calls is
    deterministic."""
    global _rng
    _rng = random.Random(int(n))


class Context(object):
    """One causal trace position: the trace id shared by every span of
    one request/round fleet-wide, this hop's span id, the head-sample
    decision, and (when continued from the wire) the parent span id."""

    __slots__ = ("trace_id", "span_id", "sampled", "parent")

    def __init__(self, trace_id: str, span_id: str, sampled: bool,
                 parent: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)
        self.parent = parent

    def traceparent(self) -> str:
        """W3C-style header/wire value:
        ``00-<trace id>-<span id>-<01|00>``."""
        return "00-%s-%s-%s" % (self.trace_id, self.span_id,
                                "01" if self.sampled else "00")

    def child(self) -> "Context":
        """A new context one hop below this one (fresh span id, this
        span id as the parent) — the value to put on the wire so the
        remote side's spans parent under the local segment."""
        return Context(self.trace_id, _new_id(16), self.sampled,
                       parent=self.span_id)

    def __repr__(self):
        return "Context(%s)" % self.traceparent()


def _new_id(nhex: int) -> str:
    return "%0*x" % (nhex, _idrng.getrandbits(nhex * 4))


def start_request(sampled: Optional[bool] = None) -> Optional[Context]:
    """Open a trace for one client request.  Returns None only when
    tracing is disabled; otherwise ALWAYS returns a context — an
    unsampled one still rides the wire so the completion site can
    retro-keep a slow tail (:func:`slow_keep`)."""
    if not _ENABLED or _RATE <= 0.0:
        return None
    if sampled is None:
        sampled = _rng.random() < _RATE
    if sampled:
        _COUNTS["sampled"] += 1
    return Context(_new_id(32), _new_id(16), sampled)


def step_trace() -> Optional[Context]:
    """Head-sample one trainer step.  None unless this step sampled —
    the unsampled path is one float compare plus one RNG draw, and
    ``MXTPU_TRACE_SAMPLE=0`` short-circuits before the draw (the
    <10us/step always-on budget)."""
    if not _ENABLED or _RATE <= 0.0:
        return None
    if _rng.random() >= _RATE:
        return None
    _COUNTS["sampled"] += 1
    return Context(_new_id(32), _new_id(16), True)


def parse(tp: Any) -> Optional[Context]:
    """``traceparent`` string -> :class:`Context`, or None on anything
    malformed (an unparseable header must never fail a request)."""
    if not tp or not isinstance(tp, str):
        return None
    parts = tp.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    tid, sid, flags = parts[1], parts[2], parts[3]
    if len(tid) != 32 or len(sid) != 16 or len(flags) != 2:
        return None
    try:
        int(tid, 16), int(sid, 16)
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    if not _ENABLED:
        return None
    return Context(tid.lower(), sid.lower(), sampled)


# -- ambient context (trainer step -> kvstore/perf hooks) -----------------

def current() -> Optional[Context]:
    """The thread's ambient context (set by the Trainer around its
    step, by the kvstore around a wire round) — how deep layers attach
    child spans without threading a ctx argument through every
    signature."""
    return getattr(_tls, "ctx", None)


def set_current(ctx: Optional[Context]) -> None:
    _tls.ctx = ctx


class use(object):
    """``with tracing.use(ctx): ...`` — scoped :func:`set_current`
    that restores the previous ambient context (None-safe)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[Context]):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


# -- span emission --------------------------------------------------------

def record_span(ctx: Optional[Context], name: str, dur_s: float,
                root: bool = False, ago: float = 0.0,
                **fields) -> Optional[Context]:
    """Emit one finished span as a telemetry ``span`` record and
    return the context OF THAT SPAN (chainable: pass its
    ``.traceparent()`` downstream so the next hop parents here).

    ``root=True`` records under ``ctx``'s own span id (the segment the
    wire context names); default mints a child id under it.  ``ago``
    shifts the span's END ``ago`` seconds before now — batch completion
    sites emit queue_wait/linger/dispatch segments together at fulfill
    time, each ending at its true instant.  Like ``step`` records, a
    span record's ``ts`` is its END; renderers subtract ``dur_s``."""
    if ctx is None:
        return None
    from . import telemetry as _tel

    if root:
        span_ctx = ctx
    else:
        span_ctx = Context(ctx.trace_id, _new_id(16), ctx.sampled,
                           parent=ctx.span_id)
    ev = _tel.record("span", name=name, dur_s=round(float(dur_s), 9),
                     trace=span_ctx.trace_id, span=span_ctx.span_id,
                     parent=span_ctx.parent, **fields)
    if ev is not None and ago:
        ev["ts"] = ev["ts"] - float(ago)
    with _lock:
        _COUNTS["spans"] += 1
        acc = _SEG.get(name)
        if acc is None:
            acc = _SEG[name] = [0, 0.0, time.time()]
        acc[0] += 1
        acc[1] += float(dur_s)
    from . import profiler as _prof

    _prof.inc_stat("trace_spans")
    return span_ctx


# -- tail-latency retro-keep ---------------------------------------------

def slow_keep(name: str, hist, value: float) -> bool:
    """The always-sample-slow escape hatch: True when ``value``
    exceeds the rolling per-window p95 of ``hist`` (a
    :class:`telemetry.Histogram` the completion site records into
    anyway).  The p95 refreshes from the histogram's interval window
    at most every ``_P95_REFRESH_S`` seconds, so the steady-state cost
    is one dict lookup and one float compare.  False until a first
    window exists (nothing to be slow against)."""
    now = time.monotonic()
    with _lock:
        st = _P95.get(name)
        if st is None:
            st = _P95[name] = [hist.state(), None, now]
            return False
        if now - st[2] >= _P95_REFRESH_S:
            snap, st[0] = hist.interval(st[0])
            if snap["count"]:
                st[1] = snap["p95"]
            st[2] = now
        p95 = st[1]
    if p95 is None or value <= p95:
        return False
    _COUNTS["retro_kept"] += 1
    from . import profiler as _prof

    _prof.inc_stat("trace_retro_keep")
    return True


_CLIENT_HIST = None


def finish_request(ctx: Optional[Context], wall_s: float,
                   name: str = "client", **fields) -> bool:
    """Client-side request completion: keep the trace when it head-
    sampled OR its wall beat the rolling p95 of this client's own
    request history (retro-keep), and emit the ROOT span (the wall the
    stitched tree reconciles against).  Returns whether it was kept."""
    if ctx is None:
        return False
    global _CLIENT_HIST
    if _CLIENT_HIST is None:
        from . import telemetry as _tel

        _CLIENT_HIST = _tel.histogram("trace_client_wall_s")
    keep = ctx.sampled or slow_keep("trace_client_wall_s",
                                    _CLIENT_HIST, wall_s)
    _CLIENT_HIST.record(wall_s)
    if keep:
        record_span(ctx, name, wall_s, root=True,
                    retro=None if ctx.sampled else True, **fields)
    return keep


# -- OpenMetrics exemplars ------------------------------------------------

def note_exemplar(name: str, trace_id: str, value: float) -> None:
    """Remember the slowest kept request for histogram ``name`` so the
    OpenMetrics exposition (`mx.obs`) can attach its trace id as an
    exemplar — p99 becomes clickable from Prometheus.  Keeps the max
    value within a ``_EXEMPLAR_WINDOW_S`` window (an old record does
    not pin the exemplar forever)."""
    now = time.time()
    with _lock:
        cur = _EXEMPLAR.get(name)
        if cur is None or value >= cur["value"] \
                or now - cur["ts"] > _EXEMPLAR_WINDOW_S:
            _EXEMPLAR[name] = {"trace_id": str(trace_id),
                               "value": float(value), "ts": now}


def exemplar(name: str) -> Optional[Dict[str, Any]]:
    """The current exemplar for histogram ``name`` (or None)."""
    with _lock:
        cur = _EXEMPLAR.get(name)
        return dict(cur) if cur else None


# -- critical-path analysis ----------------------------------------------

def _spans_of(events, trace_id: Optional[str]):
    spans = [e for e in events if e.get("kind") == "span"
             and e.get("trace") and e.get("dur_s") is not None]
    if not spans:
        return None, []
    if trace_id is None:
        by_trace: Dict[str, int] = {}
        for s in spans:
            by_trace[s["trace"]] = by_trace.get(s["trace"], 0) + 1
        trace_id = max(by_trace, key=lambda t: by_trace[t])
    return trace_id, [s for s in spans if s["trace"] == trace_id]


def critical_path(events, trace_id: Optional[str] = None
                  ) -> Optional[Dict[str, Any]]:
    """Attribute one stitched span tree.  ``events`` is a list of span
    records (telemetry events, possibly merged across roles);
    ``trace_id=None`` picks the trace with the most spans.

    Each span contributes its SELF time — duration minus its direct
    children's durations, clamped at 0 (children on another process
    clock may not nest exactly) — to its segment name, so the segment
    sum reconciles with the root span's wall by construction.  Returns
    ``{"trace", "wall_s", "spans", "pids", "segments": [{"name",
    "self_s", "frac"}...] (by share, desc), "dominant", "chain"}``
    where ``chain`` is the causal-order report string, e.g.
    ``queue_wait 41% -> batch_linger 22% -> device 30%``.  None when
    the trace has no spans."""
    trace_id, spans = _spans_of(events, trace_id)
    if not spans:
        return None
    by_id = {s.get("span"): s for s in spans if s.get("span")}
    child_sum: Dict[str, float] = {}
    for s in spans:
        p = s.get("parent")
        if p in by_id:
            child_sum[p] = child_sum.get(p, 0.0) + float(s["dur_s"])
    roots = [s for s in spans if s.get("parent") not in by_id]
    wall = max((float(s["dur_s"]) for s in roots), default=0.0)
    if wall <= 0.0:
        wall = sum(float(s["dur_s"]) for s in spans) or 1e-12
    segs: Dict[str, List[float]] = {}  # name -> [self_s, first_start]
    for s in spans:
        self_s = max(0.0, float(s["dur_s"])
                     - child_sum.get(s.get("span"), 0.0))
        start = float(s.get("ts", 0.0)) - float(s["dur_s"])
        acc = segs.get(s.get("name", "span"))
        if acc is None:
            segs[s.get("name", "span")] = [self_s, start]
        else:
            acc[0] += self_s
            acc[1] = min(acc[1], start)
    ordered = sorted(segs.items(), key=lambda kv: kv[1][1])
    chain = " -> ".join("%s %d%%" % (n, round(100.0 * v[0] / wall))
                        for n, v in ordered if v[0] / wall >= 0.01)
    by_share = sorted(segs.items(), key=lambda kv: -kv[1][0])
    return {
        "trace": trace_id,
        "wall_s": wall,
        "spans": len(spans),
        "pids": len({s.get("pid") for s in spans}),
        "segments": [{"name": n, "self_s": round(v[0], 6),
                      "frac": round(v[0] / wall, 4)}
                     for n, v in by_share],
        "dominant": by_share[0][0] if by_share else None,
        "chain": chain,
    }


# -- merge-time stitching (telemetry.merge_dir) ---------------------------

def stitch(span_events: List[Dict[str, Any]], t0: float
           ) -> Tuple[List[Dict], Dict[str, Any]]:
    """Join span records from MANY per-role snapshots into chrome-trace
    flow events (one ``s``/``t``/``f`` arrow chain per cross-process
    trace id, binding the X spans `telemetry._events_to_chrome`
    already emitted) plus the ``tracing`` rollup for cluster.json:
    trace/span totals, how many traces crossed a process boundary, and
    the critical path of the largest traces."""
    flows: List[Dict] = []
    by_trace: Dict[str, List[Dict]] = {}
    for ev in span_events:
        by_trace.setdefault(ev.get("trace"), []).append(ev)
    by_trace.pop(None, None)
    cross = 0
    flow_id = 0
    for tid, evs in sorted(by_trace.items()):
        pids = {e.get("pid") for e in evs}
        if len(pids) < 2:
            continue
        cross += 1
        flow_id += 1
        seq = sorted(evs, key=lambda e: float(e.get("ts", 0.0))
                     - float(e.get("dur_s", 0.0)))
        for i, ev in enumerate(seq):
            start_us = (float(ev.get("ts", t0))
                        - float(ev.get("dur_s", 0.0)) - t0) * 1e6
            ph = "s" if i == 0 else ("f" if i == len(seq) - 1 else "t")
            flow = {"name": "trace", "cat": "trace", "ph": ph,
                    "id": flow_id, "ts": max(0.0, start_us),
                    "pid": int(ev.get("pid", 0)), "tid": 0,
                    "args": {"trace": tid}}
            if ph == "f":
                flow["bp"] = "e"
            flows.append(flow)
    biggest = sorted(by_trace.items(), key=lambda kv: -len(kv[1]))[:3]
    paths = {}
    for tid, evs in biggest:
        cp = critical_path(evs, tid)
        if cp:
            paths[tid] = {"chain": cp["chain"],
                          "dominant": cp["dominant"],
                          "wall_s": round(cp["wall_s"], 6),
                          "spans": cp["spans"], "pids": cp["pids"]}
    rollup = {
        "traces": len(by_trace),
        "spans": sum(len(v) for v in by_trace.values()),
        "cross_process_traces": cross,
        "critical_paths": paths,
    }
    return flows, rollup


# -- metrics provider (heartbeats / obs snapshot / cluster_live) ----------

def metrics_block() -> Dict[str, Any]:
    """This role's tracing summary for ``telemetry.metrics()`` (and
    therefore heartbeats, ``/snapshot.json`` and cluster_live.json):
    sample counters plus the LOCAL dominant critical-path segment —
    which named segment owns the largest share of this role's sampled
    span time (the `tools/dash.py` crit-path column)."""
    with _lock:
        segs = {n: v[1] for n, v in _SEG.items()}
        counts = dict(_COUNTS)
    out: Dict[str, Any] = {
        "enabled": enabled(),
        "sample_rate": _RATE,
        "sampled": counts["sampled"],
        "retro_kept": counts["retro_kept"],
        "spans": counts["spans"],
    }
    total = sum(segs.values())
    if total > 0.0:
        top = sorted(segs.items(), key=lambda kv: -kv[1])[:3]
        out["dominant_segment"] = "%s %d%%" % (
            top[0][0], round(100.0 * top[0][1] / total))
        out["critical_path"] = " -> ".join(
            "%s %d%%" % (n, round(100.0 * v / total)) for n, v in top)
        out["segments_s"] = {n: round(v, 6)
                             for n, v in sorted(segs.items())}
    return out


def reset() -> None:
    """Clear accumulators + exemplars + retro-keep windows (tests)."""
    with _lock:
        _SEG.clear()
        _EXEMPLAR.clear()
        _P95.clear()
        for k in _COUNTS:
            _COUNTS[k] = 0


# register last: telemetry never imports tracing at module level, the
# provider closes the loop (the mx.perf idiom)
from . import telemetry as _tel  # noqa: E402

_tel.register_metrics_provider("tracing", metrics_block)
