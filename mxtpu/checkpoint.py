"""mx.checkpoint — fleet-consistent async checkpointing with
deterministic full-run resume (docs/checkpoint.md).

Three layers on top of the PR-2 atomic CRC-manifest machinery
(`mxtpu/resilience.py`):

* :class:`AsyncSnapshotter` — the per-role write path.  ``capture()``
  does the device→host copy into a double buffer and returns; a
  background writer thread lands the snapshot with temp+fsync+rename
  and a CRC manifest.  Steady-state checkpointing costs the copy,
  never the write: if the previous write is still in flight the new
  capture is DROPPED AND COUNTED (``ckpt_dropped``) instead of
  blocking the step.

* :class:`FleetCheckpointer` — fleet consistency over the PS round
  protocol.  The scheduler stamps an idempotent (round, generation,
  live-worker-set) checkpoint id; every worker snapshots at that exact
  round (params + optimizer state + full run state), rank 0 commands
  every server to snapshot its shard store + version vector, and rank
  0's writer thread commits ``fleet.json`` LAST — only after every
  role manifest validates.  A fleet with any missing/torn role bundle
  never gets a fleet manifest and is skipped as a unit at load.

* Resume — :func:`find_resume` picks the newest COMPLETE fleet
  checkpoint, :func:`restore_worker` restores params/optimizer/RNG/
  DataLoader position into a fresh process and anchors the kvstore
  round (``resume_at_version``) so the first post-resume push lands as
  round R+1 against the servers' restored version vectors.
  ``tools/launch.py --auto-resume`` wires this into whole-fleet
  auto-restart.

The per-role snapshot bundles the FULL run state: RNG stream
(`mx.random.get_state`), DataLoader/sampler position (epoch, batch
index, shuffle seed — `DataLoader.state()`), trainer step count, and
the applied `mx.tune` knob provenance, so a resumed run is trajectory-
identical to the uninterrupted one (`tools/check_checkpoint.py`
enforces 1e-5).
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import profiler as _prof
from . import resilience as _res
from . import telemetry as _tel

__all__ = [
    "AsyncSnapshotter", "FleetCheckpointer", "collect_run_state",
    "apply_run_state", "restore_worker", "restore_dir", "find_resume",
    "fleet_dir", "fleet_manifest_path", "read_fleet_manifest",
    "fleet_complete", "load_worker_bundle", "write_server_snapshot",
    "load_server_snapshot", "ckpt_dir", "ckpt_every", "arm", "disarm",
    "install_preemption", "on_boundary", "active", "module_bundle",
    "trainer_bundle", "snapshotter",
]

log = logging.getLogger(__name__)

FLEET_MANIFEST = "fleet.json"
FLEET_FORMAT = 1


# ---------------------------------------------------------------------------
# knobs (docs/env_vars.md)
# ---------------------------------------------------------------------------

def ckpt_dir() -> Optional[str]:
    """Where fleet checkpoints live: ``MXTPU_CKPT_DIR``, defaulting to
    the run directory (``MXTPU_RUN_DIR``)."""
    return os.environ.get("MXTPU_CKPT_DIR") or \
        os.environ.get("MXTPU_RUN_DIR") or None


def ckpt_every() -> int:
    """``MXTPU_CKPT_EVERY``: checkpoint every N step/round boundaries
    (0 = only explicit/preemption checkpoints)."""
    try:
        return int(os.environ.get("MXTPU_CKPT_EVERY", "0") or 0)
    except ValueError:
        return 0


def restore_dir() -> Optional[str]:
    """``MXTPU_CKPT_RESTORE``: a complete fleet-checkpoint directory to
    restore from (set by ``launch.py --auto-resume``)."""
    return os.environ.get("MXTPU_CKPT_RESTORE") or None


def _keep() -> int:
    try:
        return max(1, int(os.environ.get("MXTPU_CKPT_KEEP", "3") or 3))
    except ValueError:
        return 3


def _maybe_write_delay() -> None:
    """Test hook: ``MXTPU_CKPT_WRITE_DELAY`` seconds of sleep before
    the writer thread touches disk — widens the torn-write window the
    mid-write-kill chaos phase of `tools/check_checkpoint.py` aims at."""
    try:
        delay = float(os.environ.get("MXTPU_CKPT_WRITE_DELAY", "0") or 0)
    except ValueError:
        return
    if delay > 0:
        time.sleep(delay)


def _fleet_timeout() -> float:
    try:
        return float(os.environ.get("MXTPU_CKPT_FLEET_TIMEOUT", "60")
                     or 60)
    except ValueError:
        return 60.0


# ---------------------------------------------------------------------------
# full-run state (RNG / DataLoader position / tune provenance)
# ---------------------------------------------------------------------------

def collect_run_state(loaders=None, extra: Optional[Dict] = None) -> Dict:
    """JSON-able bundle of everything outside params/optimizer that a
    deterministic resume needs: the threefry RNG chain, each named
    DataLoader's (epoch, batch, seed) position, and the applied
    `mx.tune` knob provenance."""
    from . import random as _rnd

    key = _rnd.get_state()
    state: Dict[str, Any] = {
        "rng": None if key is None
        else np.asarray(key).astype(np.uint32).tolist(),
        "loaders": {},
        "tune": None,
    }
    try:
        from . import tune as _tune

        state["tune"] = _tune.current_applied()
    except Exception:
        pass
    for name, ld in dict(loaders or {}).items():
        if callable(getattr(ld, "state", None)):
            state["loaders"][str(name)] = ld.state()
    if extra:
        state["extra"] = extra
    return state


def apply_run_state(state, loaders=None) -> None:
    """Inverse of :func:`collect_run_state` — loaders are matched by
    the same names they were captured under."""
    if not state:
        return
    from . import random as _rnd

    if state.get("rng") is not None:
        _rnd.set_state(state["rng"])
    saved = state.get("loaders") or {}
    for name, ld in dict(loaders or {}).items():
        st = saved.get(str(name))
        if st is not None and callable(getattr(ld, "set_state", None)):
            ld.set_state(st)


# ---------------------------------------------------------------------------
# async double-buffered snapshot writer
# ---------------------------------------------------------------------------

def _to_host(v) -> np.ndarray:
    """Device→host copy (the only part of a capture that touches the
    device; `asnumpy` materializes a host array)."""
    if hasattr(v, "asnumpy"):
        return np.asarray(v.asnumpy())
    return np.asarray(v)


class AsyncSnapshotter(object):
    """Double-buffered background checkpoint writer (one per role).

    ``capture()`` copies arrays to host and hands the snapshot to a
    daemon writer thread; if a previous snapshot is still pending or
    being written the capture is dropped and ``ckpt_dropped`` ticks —
    the training step NEVER waits on the disk.  ``flush()`` drains for
    final/preemption snapshots."""

    def __init__(self):
        self._cv = threading.Condition()
        self._pending: Optional[Dict] = None
        self._inflight = False
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.last_error: Optional[BaseException] = None

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mxtpu-ckpt-writer")
            self._thread.start()

    def capture(self, prefix: str, epoch: int, arrays: Dict[str, Any],
                states: Optional[bytes] = None,
                extra: Optional[Dict] = None,
                post: Optional[Callable[[], None]] = None,
                wait: bool = False) -> bool:
        """Snapshot ``arrays`` (+ optional opaque ``states`` bytes +
        JSON ``extra`` recorded on the manifest) as epoch ``epoch``
        under ``prefix``.  Returns False when dropped because the
        previous write is still in flight (counted); ``wait=True``
        blocks for the writer instead (final flushes only).  ``post``
        runs on the writer thread after the manifest commits (rank 0
        hangs the fleet-manifest commit here — polling for the other
        roles happens entirely off the critical path)."""
        host = {k: _to_host(v) for k, v in arrays.items()}
        snap = {"prefix": prefix, "epoch": int(epoch), "arrays": host,
                "states": states, "extra": extra, "post": post}
        with self._cv:
            if self._pending is not None or self._inflight:
                if not wait:
                    _prof.inc_stat("ckpt_dropped")
                    return False
                while self._pending is not None or self._inflight:
                    self._cv.wait(0.1)
            self._pending = snap
            self._ensure_thread()
            self._cv.notify_all()
        _prof.inc_stat("ckpt_capture")
        if wait:
            self.flush()
            return self.last_error is None
        return True

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait(0.5)
                if self._pending is None:
                    return
                snap, self._pending = self._pending, None
                self._inflight = True
            try:
                self._write(snap)
                self.last_error = None
            except BaseException as e:
                self.last_error = e
                _prof.inc_stat("ckpt_write_failed")
                log.warning("async checkpoint write failed (%s-%04d): %s",
                            snap["prefix"], snap["epoch"], e)
            finally:
                with self._cv:
                    self._inflight = False
                    self._cv.notify_all()

    def _write(self, snap: Dict) -> None:
        _maybe_write_delay()
        prefix, epoch = snap["prefix"], snap["epoch"]
        w = _res.CheckpointWriter(prefix, epoch)
        base = "%s-%04d" % (prefix, epoch)
        with w.file(base + ".arrays.npz") as f:
            np.savez(f, **snap["arrays"])
        if snap["states"] is not None:
            with w.file(base + ".states.bin") as f:
                f.write(snap["states"])
        w.commit(extra={"bundle": snap["extra"] or {}})
        _prof.inc_stat("ckpt_async_write")
        if snap["post"] is not None:
            snap["post"]()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait for any pending/in-flight write to land."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending is not None or self._inflight:
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                self._cv.wait(0.1)
        return True

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


_SNAPSHOTTER: Optional[AsyncSnapshotter] = None
_SNAP_LOCK = threading.Lock()


def snapshotter() -> AsyncSnapshotter:
    """The process-wide snapshotter (one writer thread per role)."""
    global _SNAPSHOTTER
    with _SNAP_LOCK:
        if _SNAPSHOTTER is None:
            _SNAPSHOTTER = AsyncSnapshotter()
        return _SNAPSHOTTER


# ---------------------------------------------------------------------------
# bundle load/save formats
# ---------------------------------------------------------------------------

def load_worker_bundle(d: str, rank: int,
                       epoch: Optional[int] = None):
    """Read a worker bundle: ``(arrays, states_bytes, manifest)`` or
    None when no valid bundle exists for this rank."""
    prefix = os.path.join(d, "worker%d" % rank)
    if epoch is None:
        epoch = _res.latest_valid_epoch(prefix)
    if epoch is None or not _res.validate_manifest(prefix, epoch):
        return None
    man = _res.read_manifest(prefix, epoch)
    base = "%s-%04d" % (prefix, epoch)
    with np.load(base + ".arrays.npz", allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    states = None
    if os.path.exists(base + ".states.bin"):
        with open(base + ".states.bin", "rb") as f:
            states = f.read()
    return arrays, states, man


def write_server_snapshot(d: str, rank: int, rnd: int,
                          blob: bytes) -> None:
    """Land one PS server's shard snapshot (store + version vector +
    updater state, already pickled by `_ps.Server`) under the fleet
    checkpoint directory with its own CRC manifest."""
    _maybe_write_delay()
    prefix = os.path.join(d, "server%d" % rank)
    w = _res.CheckpointWriter(prefix, rnd)
    with w.file("%s-%04d.shard.pkl" % (prefix, rnd)) as f:
        f.write(blob)
    w.commit(extra={"bundle": {"role": "server", "rank": int(rank),
                               "round": int(rnd)}})
    _prof.inc_stat("ckpt_server_write")


def load_server_snapshot(d: str, rank: int) -> Optional[Tuple[bytes, int]]:
    """``(blob, round)`` of a server's newest valid shard snapshot."""
    prefix = os.path.join(d, "server%d" % rank)
    epoch = _res.latest_valid_epoch(prefix)
    if epoch is None:
        return None
    path = "%s-%04d.shard.pkl" % (prefix, epoch)
    with open(path, "rb") as f:
        return f.read(), epoch


# ---------------------------------------------------------------------------
# fleet manifest
# ---------------------------------------------------------------------------

def fleet_dir(base_dir: str, ckpt_id: str) -> str:
    return os.path.join(base_dir, "ckpt_%s" % ckpt_id)


def fleet_manifest_path(d: str) -> str:
    return os.path.join(d, FLEET_MANIFEST)


def read_fleet_manifest(d: str) -> Optional[Dict]:
    try:
        with open(fleet_manifest_path(d)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or "round" not in m:
        return None
    return m


def _role_prefixes(m: Dict) -> List[str]:
    return (["worker%d" % r for r in range(int(m.get("num_workers", 0)))]
            + ["server%d" % s for s in range(int(m.get("num_servers", 0)))])


def fleet_complete(d: str) -> Optional[Dict]:
    """The fleet manifest iff this checkpoint is COMPLETE: fleet.json
    committed AND every per-role manifest it names validates (CRC) —
    partial/torn fleets return None and are skipped as a unit."""
    m = read_fleet_manifest(d)
    if m is None:
        return None
    rnd = int(m["round"])
    for p in _role_prefixes(m):
        if not _res.validate_manifest(os.path.join(d, p), rnd):
            return None
    return m


def find_resume(base_dir: Optional[str]) -> Optional[Tuple[str, Dict]]:
    """Newest complete fleet checkpoint under ``base_dir`` as
    ``(directory, fleet_manifest)``, or None."""
    if not base_dir or not os.path.isdir(base_dir):
        return None
    cands = []
    for name in sorted(os.listdir(base_dir)):
        if not name.startswith("ckpt_"):
            continue
        path = os.path.join(base_dir, name)
        m = fleet_complete(path)
        if m is not None:
            cands.append((int(m["round"]), float(m.get("ts", 0)), path, m))
    if not cands:
        return None
    cands.sort(key=lambda c: (c[0], c[1], c[2]))
    _, _, path, m = cands[-1]
    return path, m


def _commit_fleet(d: str, stamp: Dict,
                  timeout: Optional[float] = None) -> bool:
    """Rank 0's writer thread: poll until EVERY role manifest for the
    stamped round validates, then commit fleet.json atomically LAST.
    The polling is the fleet synchronization — it lives on the writer
    thread, never the step.  On timeout (a role dropped its capture or
    died) no fleet manifest is written: the partial fleet stays
    invisible to resume."""
    rnd = int(stamp["round"])
    need = _role_prefixes(stamp)
    deadline = time.monotonic() + (timeout if timeout is not None
                                   else _fleet_timeout())
    while True:
        missing = [p for p in need
                   if not _res.validate_manifest(os.path.join(d, p), rnd)]
        if not missing:
            break
        if time.monotonic() >= deadline:
            _prof.inc_stat("ckpt_fleet_incomplete")
            log.warning("fleet checkpoint %s incomplete after %.0fs "
                        "(missing %s) — left uncommitted",
                        stamp.get("id"), _fleet_timeout(), missing)
            return False
        time.sleep(0.05)
    payload = dict(stamp)
    payload["format"] = FLEET_FORMAT
    payload["ts"] = time.time()
    payload["roles"] = need
    with _res.atomic_write(fleet_manifest_path(d), "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    _prof.inc_stat("ckpt_fleet_committed")
    _tel.record("checkpoint", fleet=stamp.get("id"), round=rnd,
                roles=len(need), dir=d)
    _ledger({"event": "checkpoint", "ckpt": stamp.get("id"),
             "round": rnd, "dir": d, "roles": len(need)})
    return True


def _ledger(row: Dict) -> None:
    try:
        from . import obs as _obs

        _obs.ledger_append(row)
    except Exception:
        pass


def _gc_old(base_dir: str, keep: int, protect: str) -> None:
    """Drop the oldest COMPLETE fleet checkpoints beyond ``keep``.
    Incomplete dirs are left alone (late writers may still be landing
    files into them; they cost little and are skipped at load)."""
    try:
        complete = []
        for name in sorted(os.listdir(base_dir)):
            if not name.startswith("ckpt_"):
                continue
            path = os.path.join(base_dir, name)
            if os.path.abspath(path) == os.path.abspath(protect):
                m = read_fleet_manifest(path)
            else:
                m = fleet_complete(path)
            if m is not None:
                complete.append((int(m["round"]), path))
        complete.sort()
        for _, path in complete[:-keep]:
            if os.path.abspath(path) == os.path.abspath(protect):
                continue
            shutil.rmtree(path, ignore_errors=True)
            _prof.inc_stat("ckpt_gc_removed")
    except OSError:
        pass


# ---------------------------------------------------------------------------
# capture helpers for the two trainer surfaces
# ---------------------------------------------------------------------------

def module_bundle(module, save_optimizer_states: bool = True):
    """``(arrays, states_bytes)`` for a bound `mx.mod.Module` — params
    synced from devices; optimizer state via the kvstore updater / the
    ZeRO-1 gather wire format when initialized."""
    arg, aux = module.get_params()
    arrays = {}
    for k, v in arg.items():
        arrays["arg:%s" % k] = v
    for k, v in aux.items():
        arrays["aux:%s" % k] = v
    states = None
    if save_optimizer_states and module.optimizer_initialized:
        try:
            states = module._optimizer_state_bytes()
        except Exception as e:
            log.warning("checkpoint: optimizer state skipped: %s", e)
    return arrays, states


def trainer_bundle(trainer, save_optimizer_states: bool = True):
    """``(arrays, states_bytes)`` for a `gluon.Trainer` — parameter
    data plus the updater/ZeRO-1 gathered state (`get_states` wire
    format, replica-count independent)."""
    arrays = {}
    for p in trainer._params:
        arrays["param:%s" % p.name] = p.data()
    states = None
    if save_optimizer_states:
        upd = getattr(trainer, "_zero1", None)
        if upd is None:
            upds = getattr(trainer, "_updaters", None)
            upd = upds[0] if upds else None
        if upd is not None:
            try:
                states = upd.get_states(dump_optimizer=True)
            except Exception as e:
                log.warning("checkpoint: optimizer state skipped: %s", e)
    return arrays, states


def _apply_arrays_to_module(module, arrays: Dict[str, np.ndarray]) -> None:
    from .ndarray import array as nd_array

    arg = {k[len("arg:"):]: nd_array(v) for k, v in arrays.items()
           if k.startswith("arg:")}
    aux = {k[len("aux:"):]: nd_array(v) for k, v in arrays.items()
           if k.startswith("aux:")}
    module.init_params(initializer=None, arg_params=arg, aux_params=aux,
                       allow_missing=True, force_init=True,
                       allow_extra=True)


def _apply_arrays_to_trainer(trainer, arrays: Dict[str, np.ndarray]) -> None:
    from .ndarray import array as nd_array

    by_name = {p.name: p for p in trainer._params}
    for k, v in arrays.items():
        if not k.startswith("param:"):
            continue
        p = by_name.get(k[len("param:"):])
        if p is not None:
            p.set_data(nd_array(v))


# ---------------------------------------------------------------------------
# the fleet checkpointer
# ---------------------------------------------------------------------------

class FleetCheckpointer(object):
    """Periodic + on-demand fleet-consistent checkpoints.

    ``kv=None`` runs in single-process mode (no stamp RPC, no server
    command — the fleet is just this worker, and ``fleet.json`` commits
    right after the local bundle lands).  With a `dist*` kvstore the
    scheduler stamps the checkpoint id so every worker lands the SAME
    (round, generation, live-worker-set) snapshot."""

    def __init__(self, kv=None, module=None, trainer=None,
                 get_bundle: Optional[Callable[[], Tuple[Dict, Optional[bytes]]]] = None,
                 loaders=None, directory: Optional[str] = None,
                 every: Optional[int] = None,
                 keep: Optional[int] = None,
                 extra_meta: Optional[Dict] = None):
        if get_bundle is None:
            if module is not None:
                get_bundle = lambda m=module: module_bundle(m)  # noqa: E731
            elif trainer is not None:
                get_bundle = lambda t=trainer: trainer_bundle(t)  # noqa: E731
            else:
                raise ValueError(
                    "FleetCheckpointer needs module=, trainer= or "
                    "get_bundle=")
        self._kv = kv
        self._get_bundle = get_bundle
        self._loaders = dict(loaders or {})
        self._dir = directory or ckpt_dir()
        if not self._dir:
            raise ValueError(
                "no checkpoint directory: pass directory= or set "
                "MXTPU_CKPT_DIR / MXTPU_RUN_DIR")
        self._every = ckpt_every() if every is None else int(every)
        self._keep_n = _keep() if keep is None else int(keep)
        self._extra_meta = extra_meta
        self._snap = snapshotter()
        self.last_id: Optional[str] = None

    @property
    def rank(self) -> int:
        return int(getattr(self._kv, "rank", 0))

    @property
    def every(self) -> int:
        return self._every

    def maybe_checkpoint(self, step: int) -> bool:
        """The step/round-boundary hook: checkpoint when ``step`` hits
        the cadence; costs one modulo otherwise."""
        if self._every > 0 and step > 0 and step % self._every == 0:
            return self.checkpoint(step)
        return False

    def _stamp(self, rnd: int) -> Dict:
        if self._kv is not None:
            return self._kv.checkpoint_stamp(rnd)
        return {"id": "r%06d_g%03d" % (rnd, 0), "round": int(rnd),
                "gen": 0, "num_workers": 1, "num_servers": 0,
                "workers": []}

    def checkpoint(self, step: int, wait: bool = False) -> bool:
        """Snapshot this worker (and, from rank 0, command the servers
        + commit the fleet manifest) at round ``step``.  Non-blocking
        by default: returns False if dropped because the previous
        write is still in flight."""
        rnd = int(step)
        stamp = self._stamp(rnd)
        d = fleet_dir(self._dir, stamp["id"])
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            log.warning("checkpoint dir %s: %s", d, e)
            return False
        rank = self.rank
        if self._kv is not None and rank == 0 and \
                int(stamp.get("num_servers", 0)) > 0:
            self._kv.server_checkpoint(d, stamp)
        arrays, states = self._get_bundle()
        meta = {"role": "worker", "rank": rank, "step": int(step),
                "stamp": stamp,
                "run_state": collect_run_state(self._loaders,
                                               extra=self._extra_meta)}
        post = None
        if rank == 0:
            base, keep_n = self._dir, self._keep_n

            def post(d=d, stamp=stamp, base=base, keep_n=keep_n):
                if _commit_fleet(d, stamp):
                    _gc_old(base, keep_n, protect=d)
        ok = self._snap.capture(
            prefix=os.path.join(d, "worker%d" % rank), epoch=rnd,
            arrays=arrays, states=states, extra=meta, post=post,
            wait=wait)
        if ok:
            self.last_id = stamp["id"]
        return ok

    def flush(self, timeout: Optional[float] = None) -> bool:
        return self._snap.flush(timeout)


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------

def restore_worker(kv=None, module=None, trainer=None, loaders=None,
                   directory: Optional[str] = None,
                   apply_states: bool = True) -> Optional[Dict]:
    """Restore this worker from a complete fleet checkpoint.

    Reads ``directory`` (default ``MXTPU_CKPT_RESTORE``), loads the
    bundle for this worker's RANK (scheduler-assigned — whichever
    process gets rank r restores bundle r), applies params to the
    module/trainer, restores RNG + DataLoader positions, and anchors
    the kvstore push/pull round at the recorded round so the first
    post-resume push lands as round R+1 against the servers' restored
    version vectors.  Call it AFTER ``bind()``/``init_params()`` and
    BEFORE ``init_optimizer()`` (the kvstore init of a restored key is
    a server-side no-op and the first pull returns the restored
    authoritative values).

    Returns the bundle meta (``step``, ``stamp``...) or None when no
    restore is armed."""
    d = directory or restore_dir()
    if not d:
        return None
    fleet = read_fleet_manifest(d)
    rank = int(getattr(kv, "rank", 0))
    found = load_worker_bundle(d, rank,
                               epoch=None if fleet is None
                               else int(fleet["round"]))
    if found is None:
        raise _res_error("no valid worker%d bundle under %s" % (rank, d))
    arrays, states, man = found
    meta = man.get("bundle", {}) or {}
    if module is not None:
        _apply_arrays_to_module(module, arrays)
    if trainer is not None:
        _apply_arrays_to_trainer(trainer, arrays)
        if apply_states and states is not None:
            # force the updater topology into existence first (the
            # ZeRO-1 updater is built lazily at _init_kvstore) so the
            # states land in the updater the steps will actually use
            if not getattr(trainer, "_kv_initialized", True):
                trainer._init_kvstore()
            upd = getattr(trainer, "_zero1", None)
            if upd is None:
                upds = getattr(trainer, "_updaters", None)
                upd = upds[0] if upds else None
            if upd is not None:
                # ZeRO-1 set_states re-shards under the ACTIVE plan:
                # a bundle written at N replicas resumes at M
                upd.set_states(states)
        if hasattr(trainer, "_num_steps"):
            trainer._num_steps = int(meta.get("step", 0))
    stamp = meta.get("stamp", {}) or {}
    rnd = int(stamp.get("round", man.get("epoch", 0)))
    if kv is not None and hasattr(kv, "resume_at_version"):
        kv.resume_at_version(rnd)
    apply_run_state(meta.get("run_state"), loaders)
    out = {"dir": d, "rank": rank, "round": rnd,
           "step": int(meta.get("step", rnd)),
           "id": stamp.get("id"), "states": states}
    _prof.inc_stat("ckpt_restored")
    _tel.record("resume", ckpt=stamp.get("id"), round=rnd,
                step=out["step"], rank=rank, dir=d)
    _ledger({"event": "resume", "ckpt": stamp.get("id"), "round": rnd,
             "step": out["step"], "rank": rank, "dir": d})
    log.info("mx.checkpoint: restored rank %d from %s (round %d, "
             "step %d)", rank, d, rnd, out["step"])
    return out


def _res_error(msg):
    from .base import MXNetError

    return MXNetError(msg)


# ---------------------------------------------------------------------------
# boundary hook + preemption (SIGTERM -> checkpoint-then-drain)
# ---------------------------------------------------------------------------

_AUTO: Optional[FleetCheckpointer] = None
_PREEMPT: Optional[Tuple[FleetCheckpointer, bool, int]] = None
_PREEMPT_DONE = threading.Event()
_PREEMPT_REMOVE: Optional[Callable[[], None]] = None


def arm(fc: FleetCheckpointer) -> None:
    """Arm periodic boundary checkpointing: `gluon.Trainer.step` and
    `FusedTrainLoop` call :func:`on_boundary` at every step / K-step
    boundary, which delegates to ``fc.maybe_checkpoint``."""
    global _AUTO
    _AUTO = fc


def disarm() -> None:
    global _AUTO, _PREEMPT, _PREEMPT_REMOVE
    _AUTO = None
    _PREEMPT = None
    if _PREEMPT_REMOVE is not None:
        try:
            _PREEMPT_REMOVE()
        except Exception:
            pass
        _PREEMPT_REMOVE = None
    _PREEMPT_DONE.clear()


def active() -> bool:
    """Cheap per-step gate for the boundary hook."""
    return _AUTO is not None or _PREEMPT is not None


def install_preemption(fc: FleetCheckpointer, exit_after: bool = True,
                       exit_code: int = 0) -> None:
    """SIGTERM → checkpoint-then-drain: on preemption the NEXT step /
    K-step boundary flushes one final fleet snapshot synchronously
    (``wait=True`` — the writer is drained, rank 0 commits the fleet
    manifest) and then exits cleanly, so ``--auto-resume`` restarts
    from the exact boundary the signal landed on.  The handler itself
    only sets a flag (`resilience.preempted`); all real work happens
    at the boundary, never in signal context."""
    global _PREEMPT, _PREEMPT_REMOVE
    _PREEMPT = (fc, bool(exit_after), int(exit_code))
    _PREEMPT_DONE.clear()
    if _PREEMPT_REMOVE is None:
        _PREEMPT_REMOVE = _res.install_preemption_hook(
            lambda: None, forward=False)


def on_boundary(step: int) -> None:
    """Called by the training surfaces at every step/K-step boundary
    (guarded by :func:`active` so the unarmed cost is one global
    read)."""
    fc = _AUTO
    if fc is not None and not _res.preempted():
        try:
            fc.maybe_checkpoint(step)
        except Exception as e:
            _prof.inc_stat("ckpt_boundary_failed")
            log.warning("boundary checkpoint failed at step %d: %s",
                        step, e)
    if _PREEMPT is not None and _res.preempted() and \
            not _PREEMPT_DONE.is_set():
        _PREEMPT_DONE.set()
        pfc, exit_after, exit_code = _PREEMPT
        try:
            pfc.checkpoint(step, wait=True)
            _prof.inc_stat("ckpt_preempt_flushed")
            _tel.record("checkpoint", reason="preemption", step=step,
                        fleet=pfc.last_id)
            log.info("mx.checkpoint: preemption snapshot flushed at "
                     "step %d (%s)", step, pfc.last_id)
        except Exception as e:
            _prof.inc_stat("ckpt_preempt_failed")
            log.warning("preemption snapshot failed at step %d: %s",
                        step, e)
        if exit_after:
            raise SystemExit(exit_code)
