"""Base utilities for mxtpu: errors, dtypes, env config, small helpers.

TPU-native re-design of the roles played by the reference's
`include/mxnet/base.h`, `python/mxnet/base.py` and dmlc-core's
`logging.h`/`GetEnv` (reference: /root/reference). There is no ctypes
boundary here: the "C API" of the reference collapses into Python calling
straight into the JAX/XLA runtime, so `base` only carries the shared
vocabulary (dtype codes, error type, env-var config in the MXNET_* style).
"""
from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "MXNetError",
    "MXTPUError",
    "KVStoreTimeoutError",
    "PSConnectError",
    "ServerDiedError",
    "MemoryExhaustedError",
    "RequestShedError",
    "string_types",
    "numeric_types",
    "integer_types",
    "mx_real_t",
    "mx_uint",
    "_Null",
    "dtype_np_to_mx",
    "dtype_mx_to_np",
    "np_dtype",
    "getenv",
    "getenv_int",
    "getenv_bool",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (name kept for API parity with the
    reference's ``mxnet.base.MXNetError``)."""


# Alias under the new name; both are exported.
MXTPUError = MXNetError


class KVStoreTimeoutError(MXNetError, TimeoutError):
    """A kvstore push/pull got no server response within
    MXTPU_KVSTORE_TIMEOUT.  Subclasses TimeoutError so the resilience
    retry layer treats it as transient."""


class PSConnectError(MXNetError, ConnectionError):
    """The PS transport could not reach a peer within its
    backoff+deadline budget (``mxtpu/_ps.py`` `_Client._connect`).
    Subclasses ConnectionError so existing transient-failure handling
    (retry/failover) still recognizes it."""


class ServerDiedError(MXNetError):
    """A parameter server was declared dead and no replica can take
    over (``MXTPU_PS_REPLICATION=0``, the replica chain is exhausted,
    or a shard was never mirrored).  Deliberately NOT an OSError
    subclass: retrying cannot fix a dead server without a replica, so
    the resilience layer propagates this immediately instead of
    spinning until the retry deadline."""


class MemoryExhaustedError(MXNetError, MemoryError):
    """Device HBM exhausted (XLA ``RESOURCE_EXHAUSTED``), re-raised by
    ``mxtpu.health.oom_scope`` with a forensic ``report`` attached:
    per-program peak/argument/temp bytes from the `mx.inspect`
    registry (programs are named by layer/block, so memory attributes
    to model parts), device allocator stats, and the top live buffers.
    Subclasses MemoryError so generic OOM handling still recognizes
    it; retrying is pointless, so the resilience retry layer does NOT
    treat it as transient."""

    def __init__(self, msg: str, report: Optional[dict] = None):
        super().__init__(msg)
        self.report = report or {}


class RequestShedError(MXNetError):
    """`mx.serve` admission control rejected a request — the tenant's
    queue cap is full, the server is draining, or the load-shedding
    policy dropped it to protect the SLO of admitted work.  Shedding
    is a DELIBERATE overload response, not a fault: clients should
    back off (or fail over to another replica), so this is neither an
    OSError (resilience would spin retrying a full queue) nor a bare
    crash.  ``reason`` is one of ``"queue_full"``, ``"draining"``,
    ``"timeout"``, ``"overload"``."""

    def __init__(self, msg: str, reason: str = "overload"):
        super().__init__(msg)
        self.reason = reason

string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

mx_real_t = np.float32
mx_uint = int


class _NullType(object):
    """Placeholder for missing attribute values (reference: graph attr
    codegen uses `_Null` to elide defaults)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "_Null"

    def __bool__(self):
        return False


_Null = _NullType()

# Type-code table mirrors the reference's mshadow dtype enum
# (3rdparty/mshadow base.h; surfaced in python/mxnet/base.py `_DTYPE_NP_TO_MX`).
# bfloat16 is first-class here (TPU native) where the reference had it only
# as an MKL extension.
_DTYPE_NP_TO_MX = {
    None: -1,
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
    np.dtype(np.bool_): 7,
    np.dtype(np.int16): 8,
    np.dtype(np.uint16): 9,
    np.dtype(np.uint32): 10,
    np.dtype(np.uint64): 11,
}
_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}

try:  # ml_dtypes ships with jax
    import ml_dtypes as _ml_dtypes

    _BFLOAT16 = np.dtype(_ml_dtypes.bfloat16)
    _DTYPE_NP_TO_MX[_BFLOAT16] = 12
    _DTYPE_MX_TO_NP[12] = _BFLOAT16
except ImportError:  # pragma: no cover
    _BFLOAT16 = None


def dtype_np_to_mx(dtype) -> int:
    """numpy dtype -> integer type code."""
    if dtype is None:
        return -1
    return _DTYPE_NP_TO_MX[np.dtype(dtype)]


def dtype_mx_to_np(code: int):
    """integer type code -> numpy dtype."""
    return _DTYPE_MX_TO_NP[code]


def np_dtype(dtype) -> np.dtype:
    """Normalize a user-provided dtype (str/np.dtype/type/'bfloat16')."""
    if dtype is None:
        return np.dtype(mx_real_t)
    if isinstance(dtype, str) and dtype == "bfloat16" and _BFLOAT16 is not None:
        return _BFLOAT16
    return np.dtype(dtype)


# ---------------------------------------------------------------------------
# Env-var config.  The reference reads ~53 MXNET_* env vars via dmlc::GetEnv
# at use sites (docs/faq/env_var.md).  We keep the same convention and accept
# both MXNET_* and MXTPU_* prefixes (MXTPU_ wins).
# ---------------------------------------------------------------------------

def getenv(name: str, default: Optional[str] = None) -> Optional[str]:
    if name.startswith("MXNET_"):
        alt = "MXTPU_" + name[len("MXNET_"):]
        if alt in os.environ:
            return os.environ[alt]
    return os.environ.get(name, default)


def getenv_int(name: str, default: int) -> int:
    val = getenv(name)
    if val is None or val == "":
        return default
    return int(val)


def getenv_bool(name: str, default: bool) -> bool:
    val = getenv(name)
    if val is None or val == "":
        return default
    return val not in ("0", "false", "False", "FALSE", "")


# os.getpid() is a real syscall (~10us under sandboxed kernels), too
# slow for per-event stamping on telemetry/profiler hot paths; cache
# it once and refresh in forked children (dataloader workers).
_pid_cache = [os.getpid()]
if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _pid_cache.__setitem__(0, os.getpid()))


def getpid_cached() -> int:
    return _pid_cache[0]


def check_call(ret: Any) -> Any:  # parity shim; no C boundary to check
    return ret


def c_str(s):  # parity shim
    return s


def _as_tuple(x) -> Tuple:
    if x is None:
        return ()
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


def shape2tuple(shape) -> Tuple[int, ...]:
    if isinstance(shape, integer_types):
        return (int(shape),)
    return tuple(int(s) for s in shape)
