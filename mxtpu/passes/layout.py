"""Layout propagation: whole-region NHWC for the conv stack.

``MXTPU_LAYOUT=nhwc`` (the pass form of the per-op
``MXTPU_CONV_LAYOUT`` hack) rewrites every 2-D ``Convolution`` /
``Pooling`` node to run natively channels-last (``layout="NHWC"``
attr, honored by the op fns) and brackets it with explicit
``transpose`` nodes.  A propagation fixpoint then SINKS the
NHWC->NCHW exit transposes downward — through unary elementwise ops,
through ``BatchNorm`` (axis 1 -> 3), and through binary elementwise
ops whose operands are both transposed the same way — until they meet
the next conv's entry transpose and cancel.  A straight
conv→bn→relu→conv stack ends up with ONE enter and ONE exit transpose
instead of a pair per op, which is exactly the graph-level
transpose-cancellation TVM's layout pass does (arXiv 1802.04799) and
what `inspect.hlo_histogram`'s ``n_transposes_surviving`` was built to
measure (ROADMAP item 2: why NHWC benched neutral).

Unlike the other default passes this one is NOT bitwise against the
NCHW graph: permuting the layout legally permutes reduction iteration
order (BatchNorm batch statistics, pooling window sums), so parity is
verified within float tolerance by `tools/check_passes.py --layout`.
The pass is inert unless requested (env or explicit pass list).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..base import getenv
from ..ops.registry import get_op
from ..symbol.symbol import Symbol, SymbolNode, _topo_order
from .core import GraphPass
from .graph import consumer_map, rewrite_entries

__all__ = ["LayoutPass", "layout_requested"]

# NCHW <-> NHWC permutations (2 spatial dims; other ranks are skipped)
_TO_CL = (0, 2, 3, 1)
_FROM_CL = (0, 3, 1, 2)

# unary shape-preserving ops a transpose commutes with exactly
_SINK_UNARY = frozenset({
    "relu", "sigmoid", "tanh", "softsign", "hard_sigmoid", "Activation",
    "LeakyReLU", "clip", "Cast", "_copy", "BlockGrad", "negative",
    "abs", "exp", "log", "sqrt", "square", "rsqrt", "reciprocal",
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_power_scalar", "_maximum_scalar",
    "_minimum_scalar",
})

# binary same-shape ops sinkable when BOTH operands are equally permuted
_SINK_BINARY = frozenset({
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_grad_add", "_maximum", "_minimum",
})


def layout_requested() -> bool:
    return (getenv("MXTPU_LAYOUT") or "").lower() == "nhwc"


def _is_transpose(node: SymbolNode) -> bool:
    return (not node.is_variable) and node.op.name == "transpose"


def _axes_of(node: SymbolNode) -> Optional[Tuple[int, ...]]:
    a = node.attrs.get("axes")
    return tuple(a) if a else None


def _compose(p1: Tuple[int, ...], p2: Tuple[int, ...]) -> Tuple[int, ...]:
    """Permutation of transpose(transpose(x, p1), p2)."""
    return tuple(p1[i] for i in p2)


def _mk_transpose(name: str, entry, axes: Tuple[int, ...]) -> SymbolNode:
    node = SymbolNode(get_op("transpose"), name, {"axes": axes}, [entry])
    node.ext_attrs = {}
    return node


def _single_consumer(cons, node) -> Optional[SymbolNode]:
    """The one consumer NODE of ``node``, or None when it has several,
    is a graph head, or is unconsumed."""
    users = cons.get(id(node), ())
    ids = {id(c) for c, _, _ in users}
    if len(ids) != 1:
        return None
    return users[0][0]  # None for a head = no sinkable consumer


class LayoutPass(GraphPass):
    name = "layout"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        stats = {"convs_rewritten": 0, "pools_rewritten": 0,
                 "transposes_inserted": 0, "transposes_cancelled": 0,
                 "sunk": 0}
        self._wrap_spatial_ops(symbol, stats)
        if stats["convs_rewritten"] or stats["pools_rewritten"]:
            self._propagate(symbol, stats)
        return stats

    # -- phase A: native-NHWC spatial ops with explicit boundaries -------
    def _wrap_spatial_ops(self, symbol: Symbol, stats) -> None:
        mapping: Dict[Tuple[int, int], Tuple] = {}
        anchors: List[Tuple[SymbolNode, SymbolNode]] = []
        for n in _topo_order(symbol._outputs):
            if n.is_variable:
                continue
            lay = str(n.attrs.get("layout") or "").upper()
            if lay not in ("", "NONE", "NCHW") \
                    or len(n.attrs.get("kernel", ()) or ()) != 2:
                continue
            if n.op.name == "Convolution":
                kind = "convs_rewritten"
            elif n.op.name == "Pooling" and not n.attrs.get("global_pool"):
                kind = "pools_rewritten"
            else:
                continue
            t_in = _mk_transpose(n.name + "__to_nhwc", n.inputs[0], _TO_CL)
            n.inputs[0] = (t_in, 0)
            n.attrs["layout"] = "NHWC"
            t_out = _mk_transpose(n.name + "__to_nchw", (n, 0), _FROM_CL)
            mapping[(id(n), 0)] = (t_out, 0)
            anchors.append((t_out, n))
            stats[kind] += 1
            stats["transposes_inserted"] += 2
        if mapping:
            # the exit transposes must keep reading the very entries the
            # mapping redirects, so their inputs are exempt from the sweep
            rewrite_entries(symbol, mapping,
                            skip={id(t) for t, _ in anchors})

    # -- phase B: sink + cancel fixpoint ---------------------------------
    def _propagate(self, symbol: Symbol, stats) -> None:
        guard = 0
        limit = 25 * max(1, len(_topo_order(symbol._outputs)))
        while guard < limit:
            guard += 1
            if not self._one_edit(symbol, stats):
                break

    def _one_edit(self, symbol: Symbol, stats) -> bool:
        nodes = _topo_order(symbol._outputs)
        cons = consumer_map(symbol)
        for n in nodes:
            if not _is_transpose(n):
                continue
            axes = _axes_of(n)
            if axes is None:
                continue
            src, src_idx = n.inputs[0]
            # merge/cancel: transpose(transpose(x)).  Safe even when the
            # inner transpose keeps other consumers (it just stays).
            if _is_transpose(src) and src_idx == 0:
                inner = _axes_of(src)
                if inner is not None:
                    combined = _compose(inner, axes)
                    if combined == tuple(range(len(combined))):
                        rewrite_entries(symbol, {(id(n), 0): src.inputs[0]})
                        stats["transposes_cancelled"] += 2
                    else:
                        n.inputs[0] = src.inputs[0]
                        n.attrs["axes"] = combined
                        stats["transposes_cancelled"] += 1
                    return True
            # sink below this transpose's single consumer
            c = _single_consumer(cons, n)
            if c is None:
                continue
            if self._sink(symbol, n, axes, c, cons, stats):
                return True
        return False

    @staticmethod
    def _swap_below(symbol, t, c) -> None:
        """Finish a sink: consumers of ``c`` now read ``t``, and ``t``
        reads ``c`` — done in an order that never forms a self-loop
        (``t`` is unreferenced during the sweep, re-anchored after)."""
        rewrite_entries(symbol, {(id(c), 0): (t, 0)})
        t.inputs[0] = (c, 0)

    def _sink(self, symbol, t, axes, c, cons, stats) -> bool:
        """Move transpose ``t`` (feeding consumer ``c``) below ``c``
        when ``c`` commutes with the permutation."""
        name = c.op.name
        if name in _SINK_UNARY:
            if len(c.inputs) != 1 or c.inputs[0][0] is not t:
                return False
            c.inputs[0] = t.inputs[0]
            self._swap_below(symbol, t, c)
            stats["sunk"] += 1
            return True
        if name in _SINK_BINARY and len(c.inputs) == 2:
            (a, ai), (b, bi) = c.inputs
            if not (_is_transpose(a) and _is_transpose(b)
                    and ai == 0 and bi == 0):
                return False
            if _axes_of(a) != axes or _axes_of(b) != axes:
                return False
            if _single_consumer(cons, a) is not c or \
                    _single_consumer(cons, b) is not c:
                return False
            c.inputs = [a.inputs[0], b.inputs[0]]
            self._swap_below(symbol, a, c)
            if b is not a:
                stats["transposes_cancelled"] += 1  # b goes unreachable
            stats["sunk"] += 1
            return True
        if name in ("BatchNorm", "BatchNorm_v1") \
                and int(c.attrs.get("axis", 1)) == 1 \
                and c.inputs and c.inputs[0][0] is t:
            c.inputs[0] = t.inputs[0]
            # dim d of t's output is dim axes[d] of t's input, so the
            # channel axis (1, NCHW) lives at axes[1] pre-transpose
            c.attrs["axis"] = axes[1]
            self._swap_below(symbol, t, c)
            stats["sunk"] += 1
            return True
        return False
