"""Shared graph machinery for the symbol-level pass framework.

Passes operate on a PRIVATE clone of the user's Symbol graph
(:func:`clone_graph`) and mutate it freely — node ``inputs`` lists and
the symbol's output entries are rewritten in place, and nodes dropped
from every input list simply vanish from the next ``_topo_order`` walk
(reachability from the heads IS liveness in this IR).  The helpers
here are the only graph-surgery primitives the individual passes use:

  * :func:`clone_graph` — structural deep copy (ops/attrs shared,
    nodes/edges private), iterative so graph depth never hits the
    Python recursion limit.
  * :func:`consumer_map` — reverse-edge index including the graph
    heads (consumer ``None``), for single-consumer/frontier tests.
  * :func:`rewrite_entries` — apply an ``(old node, out idx) -> entry``
    mapping transitively across every edge and head.
  * :func:`ensure_rng_ids` — the stable per-node RNG identity that
    makes graph rewrites safe for stochastic ops (see below).
  * :func:`make_const_node` — a constant-carrying node for the folding
    pass (the value is embedded at trace time as an XLA constant).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..ops.registry import OpDef
from ..symbol.symbol import Symbol, SymbolNode, _topo_order

Entry = Tuple[SymbolNode, int]

__all__ = ["clone_graph", "node_count", "op_node_count", "consumer_map",
           "rewrite_entries", "ensure_rng_ids", "rng_id_of",
           "make_const_node"]


def clone_graph(symbol: Symbol) -> Symbol:
    """Structurally identical private copy of ``symbol``'s graph.
    OpDefs and attr VALUES are shared (treated immutable); nodes,
    input lists, attr dicts and ext_attrs dicts are fresh.  Nodes are
    built via ``__new__`` — this runs on EVERY bind, and
    ``SymbolNode.__init__``'s AttrScope snapshot (a thread-local
    lookup + dict copy per node, immediately overwritten here) is
    measurable across a bind-heavy process."""
    memo: Dict[int, SymbolNode] = {}
    for n in _topo_order(symbol._outputs):
        new = SymbolNode.__new__(SymbolNode)
        new.op = n.op
        new.name = n.name
        new.attrs = dict(n.attrs)
        new.inputs = [(memo[id(i)], x) for i, x in n.inputs]
        new.is_aux = n.is_aux
        new.ext_attrs = dict(n.ext_attrs)
        memo[id(n)] = new
    return Symbol([(memo[id(n)], i) for n, i in symbol._outputs])


def node_count(symbol: Symbol) -> int:
    return len(_topo_order(symbol._outputs))


def op_node_count(symbol: Symbol) -> int:
    """Non-variable (executing) nodes only."""
    return sum(1 for n in _topo_order(symbol._outputs) if not n.is_variable)


def consumer_map(symbol: Symbol) -> Dict[int, List[Tuple[Optional[SymbolNode], int, int]]]:
    """id(producer) -> [(consumer node | None for a graph head,
    consumer input slot | head position, producer output idx), ...]."""
    cons: Dict[int, List[Tuple[Optional[SymbolNode], int, int]]] = {}
    for n in _topo_order(symbol._outputs):
        for s, (i, idx) in enumerate(n.inputs):
            cons.setdefault(id(i), []).append((n, s, idx))
    for s, (i, idx) in enumerate(symbol._outputs):
        cons.setdefault(id(i), []).append((None, s, idx))
    return cons


def rewrite_entries(symbol: Symbol,
                    mapping: Dict[Tuple[int, int], Entry],
                    skip=()) -> None:
    """Apply ``{(id(old node), out idx): (new node, new idx)}`` to every
    input edge and graph head, resolving chains transitively (a mapping
    target may itself be mapped).  New nodes introduced by the mapping
    are swept too (their inputs may reference remapped entries).
    ``skip`` node ids keep their inputs verbatim — for wrapper nodes
    that must keep referencing the very node the mapping redirects."""

    def resolve(e: Entry) -> Entry:
        hops = 0
        while (id(e[0]), e[1]) in mapping:
            e = mapping[(id(e[0]), e[1])]
            hops += 1
            if hops > 100000:
                raise MXNetError("pass rewrite mapping contains a cycle")
        return e

    symbol._outputs = [resolve(e) for e in symbol._outputs]
    done: set = set(skip)
    progress = True
    # fixpoint: each sweep re-walks from the heads so nodes that became
    # reachable through a rewritten edge get their own inputs rewritten
    while progress:
        progress = False
        for n in _topo_order(symbol._outputs):
            if id(n) in done:
                continue
            if n.inputs:
                n.inputs = [resolve(e) for e in n.inputs]
            done.add(id(n))
            progress = True


# ---------------------------------------------------------------------------
# Stable per-node RNG identity
# ---------------------------------------------------------------------------

def ensure_rng_ids(symbol: Symbol) -> None:
    """Assign every ``needs_rng`` node a stable ``__rng_id__`` ext attr.

    ``_build_graph_fn`` historically folded the step key by the node's
    position among RNG nodes in topo order — so ANY pass that removes or
    reorders nodes would silently renumber (reseed) downstream
    dropout-style ops.  Assigning the id once, on the ORIGINAL graph in
    topo order, keeps the unoptimized numbering bitwise identical to the
    legacy behavior while making it invariant under rewrites (clones
    copy ext_attrs, so the optimized graph folds the same ids).

    Idempotent.  Duplicate ids (a bound sub-symbol composed twice into
    one graph) are re-assigned deterministically in topo order."""
    used: set = set()
    pending: List[SymbolNode] = []
    for n in _topo_order(symbol._outputs):
        if n.is_variable or not n.op.needs_rng:
            continue
        rid = n.ext_attrs.get("__rng_id__")
        if rid is not None:
            try:
                rid = int(rid)
            except ValueError:
                rid = None
        if rid is not None and rid not in used:
            used.add(rid)
        else:
            pending.append(n)
    nxt = 0
    for n in pending:
        while nxt in used:
            nxt += 1
        n.ext_attrs["__rng_id__"] = str(nxt)
        used.add(nxt)
        nxt += 1


def rng_id_of(node: SymbolNode, fallback: int) -> int:
    """The node's stable RNG id (``fallback`` = legacy topo position,
    for graphs built before :func:`ensure_rng_ids` ran)."""
    rid = node.ext_attrs.get("__rng_id__")
    if rid is None:
        return fallback
    try:
        return int(rid)
    except ValueError:
        return fallback


# ---------------------------------------------------------------------------
# Constant nodes (folding)
# ---------------------------------------------------------------------------

def make_const_node(name: str, values: Sequence[Any]) -> SymbolNode:
    """A node evaluating to pre-computed host values.  The op is a
    per-node OpDef (NOT in the global registry): its fn closes over the
    numpy values and re-emits them at trace time, where XLA embeds them
    as program constants.  Graphs holding const nodes are for binding /
    analysis — ``tojson`` of one is not round-trippable."""
    vals = tuple(np.asarray(v) for v in values)

    def _const_fn(**_kwargs):
        import jax.numpy as jnp

        outs = tuple(jnp.asarray(v) for v in vals)
        return outs if len(outs) > 1 else outs[0]

    op = OpDef("_pass_const", _const_fn, num_outputs=len(vals),
               differentiable=False,
               doc="constant materialized by mxtpu.passes fold")
    op.const_values = vals     # value-keyed CSE + debugging
    op.amp_inline = True       # no inputs -> nothing for AMP to cast
    node = SymbolNode(op, name, {}, [])
    node.ext_attrs = {}
    return node
