"""Sharding pass: the partitioning decision as a graph annotation.

ROADMAP item 3a: item 1's `ShardingPlan` expressed as an `mx.passes`
rewrite instead of call-site pjit plumbing.  The pass stamps every
VARIABLE node of the graph with the spec the active plan assigns it —
``__shard_spec__`` (PartitionSpec string) plus ``__shard_state_dim__``
for params whose optimizer state the ZeRO-1 engine will chunk — and
reports the plan on the pass record, which is how the decision becomes
visible on `mx.inspect` program records and telemetry ``compile``
events (the acceptance contract of `tools/check_sharding.py`).

The pass is annotation-only: it never adds, removes or reorders nodes,
never touches ``__rng_id__``, and on a 1-shard plan (or none) it is a
strict no-op — so it is trivially bitwise output-identical and composes
with dce/fold/cse/fuse in any spelled order (canonical order places it
LAST, after fusion, so annotations land on the surviving variables of
the final graph).

Like ``layout``, it joins the default pass set only when requested —
here, when a `ShardingPlan` is active (`mx.shard.current_plan()`).
"""
from __future__ import annotations

from typing import Any, Dict

from ..symbol.symbol import Symbol, _topo_order
from .core import GraphPass

__all__ = ["ShardingPass", "shard_requested"]


def shard_requested() -> bool:
    """An active plan pulls ``shard`` into the default pass set — the
    ONE definition lives in `sharding.plan` (lazy import: the pass
    framework loads before the sharding package)."""
    from ..sharding.plan import shard_requested as _impl

    return _impl()


class ShardingPass(GraphPass):
    name = "shard"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        from ..sharding.plan import current_plan

        plan = current_plan()
        if plan is None or plan.num_shards <= 1:
            # 1-device mesh / no plan: strict no-op (bitwise trivially)
            return {"annotated": 0, "state_sharded": 0, "plan": None}
        annotated = state_sharded = 0
        for n in _topo_order(symbol._outputs):
            if not n.is_variable:
                continue
            shape = _known_shape(n)
            spec = plan.spec_for(n.name, shape)
            n.ext_attrs["__shard_spec__"] = str(spec)
            annotated += 1
            if shape and not n.is_aux and n.name not in plan.data_names:
                dim = plan.shard_dim(n.name, shape)
                if dim is not None:
                    n.ext_attrs["__shard_state_dim__"] = str(dim)
                    state_sharded += 1
        return {"annotated": annotated, "state_sharded": state_sharded,
                "plan": plan.describe()}


def _known_shape(node):
    """Static shape a variable declared at construction (`sym.Variable
    (shape=...)` stores ``__shape__`` in ext_attrs); () when unknown —
    spec_for treats it as replicated and shard_dim is skipped (the
    ZeRO-1 updater re-derives dims from the bound arrays anyway)."""
    shp = node.ext_attrs.get("__shape__")
    if not shp:
        return ()
    try:
        import ast

        val = ast.literal_eval(shp) if isinstance(shp, str) else shp
        return tuple(int(s) for s in val)
    except Exception:
        return ()
