"""Dead-node elimination and common-subexpression elimination.

In this IR liveness IS reachability — ``_topo_order`` walks from the
heads, so a node no input edge or head references never executes.  DCE
therefore has two jobs: forward identity nodes (``_copy``/``identity``)
past themselves so their producers connect straight to their consumers,
and let the final reachability sweep (implicit in every topo walk)
drop whatever the other passes orphaned.

CSE hashes every node by (op name, canonicalized attrs, resolved input
entries) and redirects duplicates to the first occurrence.  Variables
dedupe by (name, is_aux) — the executor maps them positionally by
name, so two variable nodes with one name are the same argument slot.
Excluded: ``needs_rng`` ops (two dropouts with identical inputs draw
DIFFERENT masks via their stable ``__rng_id__`` — merging would change
semantics), ``mutate_inputs`` ops, ``train_aware`` ops (BatchNorm-
family aux write-back is a side channel: shared-weight BNs over one
tensor each push a momentum step into the SAME aux slot, so merging
would halve the update), fused group nodes (each carries a distinct
closure under one shared op name), and any node whose attrs refuse
canonicalization (control-flow ops holding subgraph Symbols compare by
identity, which never collides).  Folded constants dedupe by VALUE
(shape/dtype/bytes), not closure identity.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ..ops.registry import canonical_attrs
from ..symbol.symbol import Symbol, _topo_order
from .core import GraphPass
from .graph import rewrite_entries

__all__ = ["DeadNodePass", "CSEPass"]

_IDENTITY_OPS = ("_copy",)  # aliases (identity) resolve to this OpDef name


class DeadNodePass(GraphPass):
    name = "dce"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        heads = {(id(n), i) for n, i in symbol._outputs}
        mapping: Dict[Tuple[int, int], Tuple] = {}
        removed = 0
        for n in _topo_order(symbol._outputs):
            if n.is_variable:
                continue
            # head identity nodes are kept so the graph's output names
            # survive (Symbol.optimize users read list_outputs)
            if n.op.name in _IDENTITY_OPS and n.inputs \
                    and (id(n), 0) not in heads:
                mapping[(id(n), 0)] = n.inputs[0]
                removed += 1
        if mapping:
            rewrite_entries(symbol, mapping)
        return {"identity_removed": removed}


def _const_key(node) -> Tuple:
    vals = node.op.const_values
    return ("_pass_const",
            tuple((tuple(v.shape), v.dtype.str, v.tobytes()) for v in vals))


class CSEPass(GraphPass):
    name = "cse"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        rep: Dict[int, Any] = {}     # id(node) -> representative node
        table: Dict[Tuple, Any] = {}
        mapping: Dict[Tuple[int, int], Tuple] = {}
        merged = 0
        # graph heads are never merged AWAY (they may be a merge
        # target): redirecting a head entry to a differently-named
        # representative would rename list_outputs() under
        # Symbol.optimize users.  XLA dedups the duplicate compute
        # inside the program anyway.
        head_ids = {id(n) for n, _ in symbol._outputs}
        for n in _topo_order(symbol._outputs):
            if n.is_variable:
                key = ("var", n.name, bool(n.is_aux))
            elif n.op.needs_rng or n.op.mutate_inputs \
                    or n.op.train_aware \
                    or getattr(n.op, "no_cse", False):
                # train_aware ops can carry side channels the key can't
                # see: two shared-weight BatchNorms over one tensor each
                # apply a momentum step to the SAME aux slot — merging
                # them would halve the update
                rep[id(n)] = n
                continue
            elif n.op.name == "_pass_const":
                key = _const_key(n)
            else:
                try:
                    ak = canonical_attrs(n.attrs)
                    key = (n.op.name, ak,
                           tuple((id(rep.get(id(i), i)), x)
                                 for i, x in n.inputs))
                    hash(key)
                except TypeError:
                    rep[id(n)] = n
                    continue
            r = table.get(key)
            if r is None or (id(n) in head_ids and not n.is_variable):
                table.setdefault(key, n)
                rep[id(n)] = n
            else:
                rep[id(n)] = r
                merged += 1
                for i in range(n.num_outputs()):
                    mapping[(id(n), i)] = (r, i)
        if mapping:
            rewrite_entries(symbol, mapping)
        return {"cse_merged": merged}
