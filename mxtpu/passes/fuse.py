"""Elementwise-chain fusion grouping.

Collapses maximal single-consumer runs of elementwise ops into ONE
fused node whose fn replays the member ops in order — the traced jaxpr
is identical primitive-for-primitive, so outputs (and vjp gradients)
are bitwise unchanged.  What changes is the graph's granularity: the
chain traces under a single ``jax.named_scope``, so `mx.inspect` HLO
attribution and device traces see one region (one layer) where XLA
fuses one kernel, instead of N per-op scopes chopping the metadata —
and graph-level tooling (node counts, bench deltas) sees the region
the way the compiler does.  The TVM/Relay analog is the
pattern-kind fusion of arXiv 1802.04799 / 1810.00952 restricted to
injective (elementwise) chains.

Chain membership: single-visible-output, deterministic, non-train-
aware ops from the elementwise whitelist; every intermediate is
consumed ONLY by the next member (so no value is computed twice) and
is not a graph head.  External operands may enter at any position.
The fused node takes the chain's terminal name (attribution lands on
the layer a user would blame) and lists its members in the
``__fused__`` ext attr.

AMP: `_build_graph_fn` applies the per-op-NAME cast policy at the node
boundary — a fused node would get the policy of its synthetic name, so
the replay applies `amp.cast_op_inputs` per MEMBER op inside the fn
(the op's `amp_inline` flag tells the graph builder to skip its own
boundary cast), keeping mixed-precision graphs bitwise identical to
their unfused form.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ..ops.registry import OpDef
from ..symbol.symbol import Symbol, SymbolNode, _topo_order
from .core import GraphPass
from .graph import consumer_map, rewrite_entries

__all__ = ["ElemwiseFusionPass", "FUSABLE_OPS"]

FUSABLE_OPS = frozenset({
    # unary elementwise
    "abs", "cbrt", "ceil", "cos", "cosh", "degrees", "erf", "erfinv",
    "exp", "expm1", "fix", "floor", "gamma", "gammaln", "log", "log10",
    "log1p", "log2", "logical_not", "negative", "radians", "rcbrt",
    "reciprocal", "rint", "round", "rsqrt", "sign", "sin", "sinh",
    "sqrt", "square", "tan", "tanh", "trunc", "arccos", "arccosh",
    "arcsin", "arcsinh", "arctan", "arctanh",
    "relu", "sigmoid", "hard_sigmoid", "softsign", "Activation",
    "LeakyReLU", "clip", "smooth_l1", "Cast", "_copy", "BlockGrad",
    "make_loss", "zeros_like", "ones_like",
    # binary / n-ary elementwise
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_grad_add", "_hypot", "_power", "_maximum", "_minimum", "_mod",
    "_equal", "_not_equal", "_greater", "_greater_equal", "_lesser",
    "_lesser_equal", "_logical_and", "_logical_or", "_logical_xor",
    "add_n",
    # broadcast binary
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_power", "broadcast_hypot",
    "broadcast_maximum", "broadcast_minimum", "broadcast_equal",
    "broadcast_not_equal", "broadcast_greater", "broadcast_greater_equal",
    "broadcast_lesser", "broadcast_lesser_equal", "broadcast_logical_and",
    "broadcast_logical_or", "broadcast_logical_xor",
    # scalar ops
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_mod_scalar", "_rmod_scalar",
    "_power_scalar", "_rpower_scalar", "_hypot_scalar", "_maximum_scalar",
    "_minimum_scalar", "_equal_scalar", "_not_equal_scalar",
    "_greater_scalar", "_greater_equal_scalar", "_lesser_scalar",
    "_lesser_equal_scalar", "_logical_and_scalar", "_logical_or_scalar",
    "_logical_xor_scalar",
})

_PREV = -1  # slot marker: the previous chain member's output


def _fusable(node: SymbolNode) -> bool:
    if node.is_variable:
        return False
    op = node.op
    return (op.name in FUSABLE_OPS and not op.needs_rng
            and not op.train_aware and not op.mutate_inputs
            and op.n_outputs(node.attrs) == 1)


def _make_fused_fn(specs):
    """Replay [(opdef, attrs, slots)] over external inputs; slot _PREV
    threads the running value.  Per-member AMP casts — see module doc."""

    def fused_fn(*ext_vals, **_kwargs):
        from .. import amp as _amp

        dt = _amp.get_compute_dtype()
        cur = None
        for opdef, attrs, slots in specs:
            ins = [cur if s == _PREV else ext_vals[s] for s in slots]
            if dt is not None:
                ins = _amp.cast_op_inputs(opdef.name, ins, dt)
            out = opdef.fn(*ins, **attrs)
            cur = out[0] if isinstance(out, tuple) else out
        return cur

    return fused_fn


class ElemwiseFusionPass(GraphPass):
    name = "fuse"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        order = _topo_order(symbol._outputs)
        cons = consumer_map(symbol)
        head_ids = {id(n) for n, _ in symbol._outputs}
        used: set = set()
        chains: List[List[SymbolNode]] = []
        for n in order:
            if id(n) in used or not _fusable(n):
                continue
            chain = [n]
            cur = n
            while True:
                users = cons.get(id(cur), ())
                ucons = {id(c) for c, _, _ in users}
                # intermediates must feed EXACTLY the next member (a
                # head output is an external consumer too)
                if len(ucons) != 1 or id(cur) in head_ids:
                    break
                nxt = users[0][0]
                if nxt is None or id(nxt) in used or not _fusable(nxt):
                    break
                chain.append(nxt)
                cur = nxt
            if len(chain) >= 2:
                used.update(id(c) for c in chain)
                chains.append(chain)

        mapping: Dict[Tuple[int, int], Tuple] = {}
        nodes_fused = 0
        for chain in chains:
            members = {id(c) for c in chain}
            ext: List[Tuple[SymbolNode, int]] = []
            specs = []
            for i, node in enumerate(chain):
                slots = []
                for (inode, idx) in node.inputs:
                    if i > 0 and inode is chain[i - 1]:
                        slots.append(_PREV)
                        continue
                    assert id(inode) not in members
                    for j, (en, ei) in enumerate(ext):
                        if en is inode and ei == idx:
                            slots.append(j)
                            break
                    else:
                        ext.append((inode, idx))
                        slots.append(len(ext) - 1)
                specs.append((node.op, dict(node.attrs), tuple(slots)))
            op = OpDef("_fused_elemwise", _make_fused_fn(specs),
                       num_outputs=1,
                       doc="elementwise chain fused by mxtpu.passes")
            op.amp_inline = True   # member-wise casts inside the fn
            op.no_cse = True       # closure identity, not attr identity
            op.fused_members = [c.name for c in chain]
            tail = chain[-1]
            fused = SymbolNode(op, tail.name, {}, ext)
            fused.ext_attrs = dict(tail.ext_attrs)
            fused.ext_attrs["__fused__"] = ",".join(c.name for c in chain)
            mapping[(id(tail), 0)] = (fused, 0)
            nodes_fused += len(chain) - 1
        if mapping:
            rewrite_entries(symbol, mapping)
        return {"chains": len(chains), "nodes_fused": nodes_fused}
