"""Constant folding: evaluate constant-only subgraphs once at bind time.

Roots are input-less initializer ops (``_zeros``/``_ones``/``_full``/
``_arange``/``_eye``); constness propagates through a whitelist of
elementwise and shape ops whose results are bit-identical whether
computed eagerly (here, per-op jit on the default backend) or inside
the whole-graph XLA program — i.e. NO cross-element reductions, whose
accumulation order may differ between fused and standalone lowerings.
``train_aware``/``needs_rng``/``mutate_inputs`` ops and anything off
the whitelist stop propagation.

The fold frontier — constant nodes with a non-constant consumer (or a
graph head) — is replaced by :func:`~mxtpu.passes.graph.make_const_node`
nodes carrying the evaluated numpy values; interior constant nodes
become unreachable and vanish.  Results above ``MXTPU_FOLD_MAX_BYTES``
(default 1 MiB) are left in the graph: embedding a giant literal in
the program buys nothing over letting XLA materialize it.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ..base import getenv_int
from ..symbol.symbol import Symbol, _topo_order
from .core import GraphPass
from .graph import consumer_map, make_const_node, rewrite_entries

__all__ = ["ConstantFoldPass"]

# input-less deterministic constant sources
_CONST_SOURCES = frozenset({"_zeros", "_ones", "_full", "_arange", "_eye"})

# constness-propagating ops: elementwise + pure shape/layout rearranges
# (NO reductions — see module doc)
_FOLD_PROP = frozenset({
    # unary elementwise
    "abs", "cbrt", "ceil", "cos", "cosh", "degrees", "erf", "exp",
    "expm1", "fix", "floor", "log", "log10", "log1p", "log2",
    "logical_not", "negative", "radians", "rcbrt", "reciprocal", "rint",
    "round", "rsqrt", "sign", "sin", "sinh", "sqrt", "square", "tan",
    "tanh", "trunc", "arccos", "arccosh", "arcsin", "arcsinh", "arctan",
    "arctanh", "relu", "sigmoid", "hard_sigmoid", "softsign",
    "Activation", "clip", "smooth_l1", "_copy", "Cast", "zeros_like",
    "ones_like", "BlockGrad", "make_loss",
    # binary / n-ary elementwise
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_grad_add", "_hypot", "_power", "_maximum", "_minimum", "_mod",
    "_equal", "_not_equal", "_greater", "_greater_equal", "_lesser",
    "_lesser_equal", "_logical_and", "_logical_or", "_logical_xor",
    "add_n",
    # broadcast binary
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_power", "broadcast_hypot",
    "broadcast_maximum", "broadcast_minimum", "broadcast_equal",
    "broadcast_not_equal", "broadcast_greater", "broadcast_greater_equal",
    "broadcast_lesser", "broadcast_lesser_equal", "broadcast_logical_and",
    "broadcast_logical_or", "broadcast_logical_xor",
    # scalar ops
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_mod_scalar", "_rmod_scalar",
    "_power_scalar", "_rpower_scalar", "_hypot_scalar", "_maximum_scalar",
    "_minimum_scalar", "_equal_scalar", "_not_equal_scalar",
    "_greater_scalar", "_greater_equal_scalar", "_lesser_scalar",
    "_lesser_equal_scalar", "_logical_and_scalar", "_logical_or_scalar",
    "_logical_xor_scalar",
    # shape / rearrange
    "Reshape", "Flatten", "transpose", "expand_dims", "squeeze",
    "SwapAxis", "moveaxis", "slice", "slice_axis", "reverse", "stack",
    "Concat", "repeat", "tile", "broadcast_axis", "broadcast_to",
    "where",
})


class ConstantFoldPass(GraphPass):
    name = "fold"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        from .. import amp as _amp

        order = _topo_order(symbol._outputs)
        # cheap pre-scan: no constant roots means nothing can fold —
        # the common case pays one walk and zero jax work
        if not any((not n.is_variable) and not n.inputs
                   and n.op.name in _CONST_SOURCES for n in order):
            return {"folded": 0}

        # the graph builder applies the per-op AMP cast policy to every
        # node's inputs; a fold that evaluated cast-free would bake
        # DIFFERENT values than the unoptimized trace computes (add_n
        # is a LOWP op).  Mirror the casts here — and the optimize
        # cache keys on the compute dtype, so a graph rebound under a
        # different policy re-folds.
        compute_dtype = _amp.get_compute_dtype()
        max_bytes = getenv_int("MXTPU_FOLD_MAX_BYTES", 1 << 20)
        values: Dict[Tuple[int, int], np.ndarray] = {}
        foldable: set = set()
        for n in order:
            if n.is_variable or n.op.needs_rng or n.op.train_aware \
                    or n.op.mutate_inputs:
                continue
            name = n.op.name
            if n.inputs:
                if name not in _FOLD_PROP:
                    continue
                if not all((id(i), x) in values for i, x in n.inputs):
                    continue
            elif name not in _CONST_SOURCES:
                continue
            try:
                import jax.numpy as jnp

                # EAGER evaluation (no per-(op, attrs) jit wrapper):
                # jax's primitive-level caches are shared process-wide,
                # so a subprocess-heavy test/deploy fleet doesn't pay a
                # fresh trace+compile per folded op.  Eager and jitted
                # lowerings of these whitelisted elementwise/shape ops
                # agree bitwise (same kernels, no reductions).
                ins = [jnp.asarray(values[(id(i), x)])
                       for i, x in n.inputs]
                if compute_dtype is not None and ins:
                    ins = _amp.cast_op_inputs(name, ins, compute_dtype)
                out = n.op.fn(*ins, **dict(n.attrs))
                if not isinstance(out, tuple):
                    out = (out,)
                outs = [np.asarray(o) for o in out]
            except Exception:
                continue  # unfoldable in practice (bad attrs, ...) — keep
            if sum(o.nbytes for o in outs) > max_bytes:
                continue
            foldable.add(id(n))
            for i, o in enumerate(outs):
                values[(id(n), i)] = o

        if not foldable:
            return {"folded": 0}
        cons = consumer_map(symbol)
        mapping: Dict[Tuple[int, int], Tuple] = {}
        folded = bytes_folded = 0
        for n in order:
            if id(n) not in foldable:
                continue
            users = cons.get(id(n), ())
            if not any(c is None or id(c) not in foldable
                       for c, _, _ in users):
                continue  # interior constant: dies with the frontier
            vals = [values[(id(n), i)] for i in range(n.num_outputs())]
            # keep the ORIGINAL node name: a folded head must not
            # rename list_outputs(), and scope attribution stays put
            cn = make_const_node(n.name, vals)
            cn.ext_attrs.update(n.ext_attrs)
            cn.ext_attrs["__folded__"] = "1"
            for i in range(n.num_outputs()):
                mapping[(id(n), i)] = (cn, i)
            folded += 1
            bytes_folded += sum(v.nbytes for v in vals)
        if mapping:
            rewrite_entries(symbol, mapping)
        return {"folded": folded, "folded_bytes": bytes_folded}
