"""GraphPass base class and the PassManager.

A pass is a named graph-to-graph rewrite over the SymbolNode DAG that
must be OUTPUT-IDENTICAL: for any inputs (and RNG key), the rewritten
graph produces the same outputs as the original — bitwise for the
default passes (dce/fold/cse/fuse, which never change the op sequence
applied to any value), within float tolerance for layout (permuting a
reduction's iteration order may legally reassociate sums).  The parity
contract is enforced by ``tools/check_passes.py`` (tier-1) across all
three dispatch paths.

The manager owns ordering: passes always execute in the canonical
order (``dce, fold, layout, cse, fuse``) regardless of how the enabled
set was spelled, because the phases feed each other — identity
elimination exposes constants, folding creates value-keyed CSE
opportunities, CSE dedupes layout's sibling-branch transposes and
lengthens single-consumer chains, and layout must see raw elementwise
ops before fusion makes them opaque.  Per-pass
wall time and node deltas land in ``profiler.stats()`` as
``pass_runs::<name>`` / ``pass_wall_us::<name>`` /
``pass_nodes_removed::<name>``.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ..base import MXNetError
from ..symbol.symbol import Symbol
from .graph import clone_graph, node_count

__all__ = ["GraphPass", "PassManager", "register_pass", "pass_names"]


class GraphPass(object):
    """Base class: subclass, set ``name``, implement :meth:`run`.

    ``run`` mutates the (already cloned, private) graph in place and
    returns a stats dict merged into the pass report.  It must preserve
    output arity/order and the name->slot mapping of surviving
    variables (the executor maps variables positionally by name)."""

    name = "graph-pass"

    def run(self, symbol: Symbol) -> Dict[str, Any]:
        raise NotImplementedError

    def __repr__(self):
        return "<GraphPass %s>" % self.name


# name -> zero-arg factory, in canonical execution order
_PASS_FACTORIES: "Dict[str, Any]" = {}
_CANONICAL: List[str] = []


def register_pass(name: str, factory) -> None:
    """Register a pass factory under ``name``; registration order IS
    the canonical execution order."""
    if name in _PASS_FACTORIES:
        raise MXNetError("graph pass %r already registered" % name)
    _PASS_FACTORIES[name] = factory
    _CANONICAL.append(name)


def pass_names() -> List[str]:
    return list(_CANONICAL)


class PassManager(object):
    """Run a set of passes over a private clone of a Symbol graph."""

    def __init__(self, passes):
        self.passes = list(passes)

    def run(self, symbol: Symbol) -> Tuple[Symbol, Dict[str, Any]]:
        from .. import profiler as _prof

        work = clone_graph(symbol)
        n0 = node_count(work)
        records: List[Dict[str, Any]] = []
        for p in self.passes:
            nb = node_count(work)
            t0 = time.perf_counter()
            stats = p.run(work) or {}
            wall_us = int((time.perf_counter() - t0) * 1e6)
            na = node_count(work)
            _prof.inc_stat("pass_runs::%s" % p.name)
            _prof.inc_stat("pass_wall_us::%s" % p.name, wall_us)
            if nb > na:
                _prof.inc_stat("pass_nodes_removed::%s" % p.name, nb - na)
            rec = {"pass": p.name, "wall_us": wall_us,
                   "nodes_before": nb, "nodes_after": na}
            rec.update(stats)
            records.append(rec)
        report = {"nodes_before": n0, "nodes_after": node_count(work),
                  "passes": records}
        return work, report
