"""mx.passes — symbol-level graph-rewrite pass framework.

The mid-level IR layer between Symbol construction and XLA tracing
(ROADMAP item 3, grounded in Relay's pass design — arXiv 1810.00952 —
and TVM's fusion/layout playbook — arXiv 1802.04799).  Every compile
path (Executor bind, CachedOp, FusedTrainLoop, control-flow subgraph
lowering) funnels through ``executor._build_graph_fn``, which calls
:func:`optimize_for_build` here, so graph-level decisions — folding,
fusion grouping, layout — are composable passes instead of call-site
hacks.

Built-in passes, in canonical execution order:

  ``dce``    identity elimination + reachability liveness
  ``fold``   constant folding (initializer-only subgraphs evaluated
             once at bind; ``MXTPU_FOLD_MAX_BYTES`` caps embeds)
  ``layout`` NHWC propagation over the conv stack (inert unless
             ``MXTPU_LAYOUT=nhwc`` or explicitly listed)
  ``cse``    common-subexpression elimination (value-keyed for folded
             constants; dedupes layout's sibling-branch transposes)
  ``fuse``   elementwise-chain fusion grouping (one node, one
             named_scope, one `mx.inspect` layer per chain)

Configuration — ``MXTPU_PASSES``:

  unset / ``1`` / ``default``   the default set above
  ``0`` / ``off`` / ``none``    disable the pipeline entirely
  ``dce,fold``                  exactly these passes
  ``default,-fuse``             the default set minus one

Spelling order never matters: the manager always executes in canonical
order.  :func:`scope` overrides the spec for a ``with`` block (tests,
A/B comparisons); `Symbol.optimize` applies a one-off spec.

Every pass is OUTPUT-IDENTICAL — bitwise for dce/fold/cse/fuse
(including RNG-consuming graphs: ``ensure_rng_ids`` pins a stable
per-node fold_in id so rewrites cannot reseed dropout), float-tolerant
for layout (reduction reassociation) — enforced in tier-1 by
``tools/check_passes.py``.  Optimized graphs are cached per (graph
identity, spec); provenance reports ride on `mx.inspect` program
records and telemetry ``compile`` events, and per-pass timings land in
``profiler.stats()``.
"""
from __future__ import annotations

import collections
import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ..base import MXNetError, getenv
from ..symbol.symbol import Symbol
from .core import (GraphPass, PassManager, pass_names, register_pass,
                   _PASS_FACTORIES)
from .graph import (clone_graph, consumer_map, ensure_rng_ids,
                    make_const_node, node_count, op_node_count,
                    rewrite_entries, rng_id_of)
from .dce_cse import CSEPass, DeadNodePass
from .fold import ConstantFoldPass
from .fuse import ElemwiseFusionPass, FUSABLE_OPS
from .layout import LayoutPass, layout_requested
from .sharding import ShardingPass, shard_requested

__all__ = [
    "GraphPass", "PassManager", "register_pass", "pass_names",
    "DeadNodePass", "CSEPass", "ConstantFoldPass", "ElemwiseFusionPass",
    "LayoutPass", "ShardingPass", "optimize", "optimize_for_build",
    "provenance_for", "provenance_summary", "ensure_rng_ids",
    "rng_id_of", "scope", "current_spec", "FUSABLE_OPS",
]

# canonical order is registration order (see core.PassManager doc).
# layout runs BEFORE cse so the entry transposes it inserts on sibling
# branches (residual blocks transpose the same tensor twice) dedupe.
# shard runs LAST (annotation-only): its specs must land on the
# variables that SURVIVE dce/fold/cse and sit under the final fused
# graph — and it must never give the rewriting passes annotated nodes
# they'd have to preserve.
register_pass("dce", DeadNodePass)
register_pass("fold", ConstantFoldPass)
register_pass("layout", LayoutPass)
register_pass("cse", CSEPass)
register_pass("fuse", ElemwiseFusionPass)
register_pass("shard", ShardingPass)

_local = threading.local()
_cache_lock = threading.Lock()
_MAX_CACHE = 128
# graph-identity key -> {"refs", "spec", "sym", "report"}
_OPT_CACHE: "collections.OrderedDict[Tuple, Dict[str, Any]]" = \
    collections.OrderedDict()


# ---------------------------------------------------------------------------
# Spec parsing / config
# ---------------------------------------------------------------------------

def _default_names() -> List[str]:
    out = []
    for n in pass_names():
        if n == "layout" and not layout_requested():
            continue
        if n == "shard" and not shard_requested():
            continue
        out.append(n)
    return out


def parse_spec(spec: Union[None, str, Sequence[str]]) -> Tuple[str, ...]:
    """Normalize a pass spec to the canonical-order tuple of names."""
    if spec is None:
        spec = getenv("MXTPU_PASSES") or "default"
    if not isinstance(spec, str):
        toks = list(spec)
    else:
        s = spec.strip().lower()
        if s in ("", "1", "on", "true", "default"):
            toks = ["default"]
        elif s in ("0", "off", "none", "false"):
            return ()
        else:
            toks = [t.strip() for t in spec.split(",") if t.strip()]
    names: set = set()
    for tok in toks:
        if tok in ("default", "all"):
            names |= set(_default_names() if tok == "default"
                         else pass_names())
            continue
        neg = tok.startswith("-")
        t = tok[1:] if neg else tok
        if t not in _PASS_FACTORIES:
            raise MXNetError(
                "unknown graph pass %r (known: %s; spec grammar: "
                "'default', 'off', 'dce,fold', 'default,-fuse')"
                % (t, ",".join(pass_names())))
        (names.discard if neg else names.add)(t)
    return tuple(n for n in pass_names() if n in names)


_SPEC_MEMO: Dict[Tuple, Tuple[str, ...]] = {}


def current_spec() -> Tuple[str, ...]:
    """The active pass set: a :func:`scope` override if one is live,
    else ``MXTPU_PASSES`` (re-read per call — flip it between binds).
    Parses are memoized by (raw string, layout request) — this runs on
    every graph build."""
    ov = getattr(_local, "spec", None)
    if ov is not None:
        return ov
    raw = getenv("MXTPU_PASSES") or "default"
    memo_key = (raw, layout_requested(), shard_requested())
    spec = _SPEC_MEMO.get(memo_key)
    if spec is None:
        spec = parse_spec(raw)
        if len(_SPEC_MEMO) > 64:
            _SPEC_MEMO.clear()
        _SPEC_MEMO[memo_key] = spec
    return spec


class scope(object):
    """``with passes.scope("off"): ...`` — override the pass spec for
    graphs BUILT inside the block (bind/hybridize time, like amp).
    ``None`` resolves to the active ``MXTPU_PASSES`` configuration —
    the same convention as ``optimize(passes=None)``."""

    def __init__(self, spec: Union[None, str, Sequence[str]]):
        self._spec = parse_spec(spec)

    def __enter__(self):
        self._old = getattr(_local, "spec", None)
        _local.spec = self._spec
        return self

    def __exit__(self, *exc):
        _local.spec = self._old


# ---------------------------------------------------------------------------
# Optimize + cache + provenance
# ---------------------------------------------------------------------------

def _cache_key(symbol: Symbol) -> Tuple:
    return tuple((id(n), i) for n, i in symbol._outputs)


def _entry_alive(ent: Dict[str, Any]) -> bool:
    return all(r() is not None for r in ent["refs"])


def optimize(symbol: Symbol,
             passes: Union[None, str, Sequence[str]] = None
             ) -> Tuple[Symbol, Optional[Dict[str, Any]]]:
    """Run the pass pipeline over ``symbol`` (uncached, explicit spec).
    Returns ``(optimized symbol, report)`` — ``(symbol, None)`` when
    the spec resolves empty.  The input graph is never mutated beyond
    RNG-id stamping (which is semantics-preserving and idempotent)."""
    names = parse_spec(passes) if passes is not None else current_spec()
    if not names:
        return symbol, None
    ensure_rng_ids(symbol)
    mgr = PassManager([_PASS_FACTORIES[n]() for n in names])
    opt, report = mgr.run(symbol)
    report["spec"] = ",".join(names)
    return opt, report


def optimize_for_build(symbol: Symbol
                       ) -> Tuple[Symbol, Optional[Dict[str, Any]]]:
    """The `_build_graph_fn` entry point: :func:`optimize` under the
    active spec, memoized per (graph identity, spec) so an Executor's
    infer/train builds — and FusedTrainLoop rebuilding the same bound
    symbol — optimize once."""
    names = current_spec()
    if not names:
        return symbol, None
    key = _cache_key(symbol)
    from .. import amp as _amp

    # fold bakes values under the ACTIVE compute-dtype policy, so the
    # same graph bound under a different amp scope must re-optimize;
    # likewise shard stamps the ACTIVE plan's specs, so a plan change
    # (or deactivation) invalidates the memo
    spec = ",".join(names) + "|amp=%s" % _amp.get_compute_dtype()
    if "shard" in names:
        from ..sharding.plan import current_plan as _cur_plan

        plan = _cur_plan()
        spec += "|plan=%s" % (plan.describe() if plan is not None else "-")
    with _cache_lock:
        ent = _OPT_CACHE.get(key)
        if ent is not None and ent["spec"] == spec and _entry_alive(ent):
            _OPT_CACHE.move_to_end(key)
            return ent["sym"], ent["report"]
    opt, report = optimize(symbol, names)
    with _cache_lock:
        _OPT_CACHE[key] = {
            "refs": [weakref.ref(n) for n, _ in symbol._outputs],
            "spec": spec, "sym": opt, "report": report,
        }
        _OPT_CACHE.move_to_end(key)
        while len(_OPT_CACHE) > _MAX_CACHE:
            _OPT_CACHE.popitem(last=False)
    return opt, report


def provenance_for(symbol) -> Optional[Dict[str, Any]]:
    """The pass report of the most recent :func:`optimize_for_build`
    of this graph (any spec), or None — how `mx.inspect` attaches
    pass provenance to program records."""
    try:
        key = _cache_key(symbol)
    except Exception:
        return None
    with _cache_lock:
        ent = _OPT_CACHE.get(key)
        if ent is not None and _entry_alive(ent):
            return ent["report"]
    return None


def provenance_summary(report: Optional[Dict[str, Any]]) -> Optional[str]:
    """Compact provenance string for telemetry ``compile`` events,
    e.g. ``"dce,fold,cse,fuse:34->21"``."""
    if not report:
        return None
    return "%s:%d->%d" % (report.get("spec", "?"),
                          report.get("nodes_before", 0),
                          report.get("nodes_after", 0))


def reset_cache() -> None:
    """Drop memoized optimized graphs (tests)."""
    with _cache_lock:
        _OPT_CACHE.clear()
