"""Fused multi-step training: K train steps per device dispatch.

TPU-native counterpart of the reference's engine-level op bulking
(`src/engine/threaded_engine.h:411-426` BulkStatus; executor bulk
segments `src/executor/graph_executor.cc:1186`).  The reference
amortizes per-op scheduling cost by fusing engine ops into segments;
on TPU the analogous overhead is per-PROGRAM dispatch latency — for a
remote PJRT client every host->device round trip costs tens of
milliseconds, and dependent dispatches cannot pipeline.  So the
TPU-first design lifts the bulking one level higher: forward, backward
AND the optimizer update for K consecutive batches are traced into ONE
XLA program (`lax.scan` over the staged batches), with the parameter,
optimizer-state and aux buffers donated (`jax.jit(donate_argnums=...)`)
so XLA updates them in place instead of allocating fresh HBM each step.

Measured on the single-chip tunnel (ResNet-50-scale): a chained
per-step dispatch stream sustains ~9 dispatches/s regardless of batch
size, while the same math inside one scanned program runs at compute
speed (a 4096^2 bf16 matmul chain hit ~196 TFLOPS — chip peak).

Semantics are EXACTLY the per-step path's: the optimizer's lr schedule
and bias-correction advance per step (effective lrs are precomputed
host-side for the K steps and fed through the scan), BatchNorm moving
stats update per step in the carry, and dropout keys fold per global
step index.  Equivalence is asserted by `tests/test_fused_train.py`.

Usage (single-device Module, local/absent kvstore)::

    loop = FusedTrainLoop(module, steps_per_program=8)
    for chunk in chunks_of(batches, 8):
        outputs = loop.run(chunk)          # ONE dispatch, 8 steps
    loop.finalize()  # publish params/opt state + drain the deferred
                     # health read (guard-off non-finite detection for
                     # the LAST chunk happens here — do call it)
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError
from .executor import _build_graph_fn
from .ndarray.ndarray import NDArray
from . import checkpoint as _ckpt
from . import health as _health
from . import perf as _perf
from . import resilience as _res
from . import xprof as _xprof

__all__ = ["FusedTrainLoop"]

_OOM_RUN = _health.oom_scope("fused_train")


class FusedTrainLoop(object):
    """Compile a Module's whole train step (fwd+bwd+optimizer) into one
    donated XLA program that scans over ``steps_per_program`` batches.

    Requirements: module is bound for training on ONE device with
    params initialized and a local (non-kvstore) optimizer whose type
    has a `make_scan_step` form (SGD / Adam), all grad_req in
    {write, null}.  Raises MXNetError otherwise.
    """

    def __init__(self, module, steps_per_program: Optional[int] = None,
                 collect_outputs: bool = True, unroll: Optional[int] = None):
        import os

        import jax

        if steps_per_program is None:
            # MXTPU_STEPS_PER_PROGRAM: the `mx.tune` registered knob —
            # an explicit constructor arg always wins over the env
            steps_per_program = int(
                os.environ.get("MXTPU_STEPS_PER_PROGRAM", "8") or 8)
        if not (module.binded and module.params_initialized and
                module.optimizer_initialized):
            raise MXNetError("FusedTrainLoop: module must be bound, "
                             "initialized and have an optimizer")
        if len(module._context) != 1:
            raise MXNetError("FusedTrainLoop: single-device modules only "
                             "(use kvstore='tpu' data parallelism for "
                             "multi-device)")
        if module._update_on_kvstore or module._kvstore is not None:
            raise MXNetError("FusedTrainLoop: kvstore-backed updates not "
                             "supported; init_optimizer(kvstore=None)")
        self._module = module
        self._exec = module._exec_group.execs[0]
        self._K = int(steps_per_program)
        self._collect = collect_outputs
        if self._K < 1:
            raise MXNetError("steps_per_program must be >= 1")
        ex = self._exec
        if any(r not in ("write", "null") for r in ex._grad_req):
            raise MXNetError("FusedTrainLoop: grad_req 'add' not supported")

        self._arg_names = ex._arg_names
        self._diff_idx = list(ex._diff_idx)
        data_names = set(module._data_names) | set(module._label_names)
        self._data_idx = [i for i, n in enumerate(self._arg_names)
                          if i not in set(self._diff_idx)
                          and n in data_names]
        self._fixed_idx = [i for i in range(len(self._arg_names))
                           if i not in set(self._diff_idx)
                           and i not in set(self._data_idx)]

        # updater-index of each carried param (single device: index =
        # position in exec_group.param_names, matching idx2name)
        pname_pos = {n: i for i, n in
                     enumerate(module._exec_group.param_names)}
        self._opt_indices = [pname_pos[self._arg_names[i]]
                             for i in self._diff_idx]

        optimizer = module._optimizer
        weights = [ex.arg_arrays[i] for i in self._diff_idx]
        self._scan_step = optimizer.make_scan_step(self._opt_indices,
                                                   weights)
        if self._scan_step is None:
            raise MXNetError("FusedTrainLoop: optimizer %r has no scan "
                             "step form" % type(optimizer).__name__)
        self._optimizer = optimizer
        self._updater = module._updater

        # device-resident state tree, seeded from the updater's states
        # (created on demand) so switching per-step <-> fused mid-train
        # is seamless
        self._state_objs = []
        for idx, w in zip(self._opt_indices, weights):
            if idx not in self._updater.states:
                self._updater.states[idx] = \
                    optimizer.create_state_multi_precision(idx, w)
                self._updater.states_synced[idx] = True
            self._state_objs.append(self._updater.states[idx])
        if any(s is not None for s in self._state_objs):
            self._s_tree = self._scan_step.pack_states(self._state_objs)
        else:
            self._s_tree = self._scan_step.init_states(
                [w._data for w in weights])
        self._p_vals = [w._data for w in weights]
        self._aux_vals = [a._data for a in ex.aux_arrays]
        self._t = 0  # global step counter (dropout key folding)

        # XLA:CPU barely parallelizes inside while-loop bodies (a rolled
        # scan of convs runs ~70x slower than the same ops unrolled), so
        # on CPU the scan defaults to fully unrolled; on TPU the rolled
        # form compiles K x faster with identical runtime.  Override via
        # the arg or MXTPU_FUSED_UNROLL.
        if unroll is None:
            env = os.environ.get("MXTPU_FUSED_UNROLL")
            if env is not None:
                unroll = max(1, int(env))
            else:
                unroll = self._K if jax.default_backend() == "cpu" else 1
        self._unroll = min(self._K, max(1, int(unroll)))

        # graceful degradation (MXTPU_MAX_BAD_STEPS > 0): each scanned
        # step checks its gradients for NaN/Inf INSIDE the program and
        # keeps the previous params/opt-state/aux when they are not
        # finite; the per-step bad flags come back to the host, which
        # aborts after that many CONSECUTIVE skips.  Note the
        # optimizer's num_update still advances for skipped steps (the
        # lr schedule stays aligned with wall steps).
        # mx.shard: an active SPMD plan (mesh + ZeRO-1) shards the
        # scanned optimizer-state carry over the mesh's data axis —
        # params stay replicated, each device holds 1/N of every
        # moment, and GSPMD compiles the reduce-scatter/allgather into
        # the K-step program itself (arXiv 2004.13336 — this is the
        # "fused K-step loop composes with it" half of ROADMAP item 1)
        self._shard_plan = None
        self._carry_pin = None
        self._init_sharded_carry(weights)

        self._guard = _res.BadStepGuard(site="fused_train") \
            if _res.max_bad_steps() > 0 else None
        # health observatory (mx.health): even without the guard, the
        # scanned program carries per-step grad finiteness + the global
        # grad norm out (one fused reduction — the always-on cheap
        # mode).  Guard armed => flags are read synchronously (the
        # skip/abort contract needs them NOW); guard off => the flags
        # are read one chunk LATER so the loop never stalls on them.
        self._track_health = self._guard is not None or _health.enabled()
        self._stats_on = _health.enabled() and _health.stats_every() > 0
        self._stats_count = 0
        self._pending_health = None  # (t0, key, stack, bad_dev, gn_dev)

        self._jit_program = jax.jit(self._make_program(),
                                    donate_argnums=(0, 1, 2))

        # program-inspector registry record (mx.inspect): the fused
        # K-step program is a first-class compile site — signature =
        # the staged data stacks (params/opt-state shapes are fixed)
        from . import inspect as _insp

        self._insp = _insp.program(
            "fused_train", ex._symbol.name,
            arg_names=[self._arg_names[i] for i in self._data_idx],
            symbol=ex._symbol)
        # device-memory layout (mx.hbm): the program tree is (p_vals,
        # s_tree, aux_vals, fixed_vals, base_key, t0, data_stack,
        # lr_rows) — params/opt-state/aux are the donated carry, the
        # stacks are (K, B, ...) input data
        self._insp.mem_layout = {
            "layout": "fused_train",
            "param_names": [self._arg_names[i] for i in self._diff_idx],
            "aux_names": list(ex._aux_names),
            "fixed_names": [self._arg_names[i] for i in self._fixed_idx],
            "data_names": [self._arg_names[i] for i in self._data_idx],
        }
        self._seen_sigs: set = set()

    def _init_sharded_carry(self, weights) -> None:
        """Re-place the scan carry for an active SPMD ShardingPlan:
        optimizer state sharded per `plan.opt_state_spec`, params/aux
        replicated and PINNED so GSPMD cannot drift the forward into a
        partitioned (reassociated) computation.  No-op without a plan
        mesh."""
        import jax

        from . import sharding as _shard

        plan = _shard.current_plan()
        if plan is None or plan.mesh is None \
                or not plan.shard_optimizer_state \
                or int(np.prod(plan.mesh.devices.shape)) <= 1:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        tu = jax.tree_util
        mesh = plan.mesh
        rep = NamedSharding(mesh, P())
        names = [self._arg_names[i] for i in self._diff_idx]
        w_shardings = [
            NamedSharding(mesh, plan.opt_state_spec(n, w.shape))
            for n, w in zip(names, weights)]
        leaves, treedef = tu.tree_flatten(self._s_tree)
        k = len(weights)
        if k == 0 or not leaves or len(leaves) % k != 0:
            # no optimizer state (e.g. momentum-free SGD) = nothing to
            # shard, no collectives to account — stay unsharded
            return
        s_shard_leaves = w_shardings * (len(leaves) // k)
        s_shardings = tu.tree_unflatten(treedef, s_shard_leaves)
        self._p_vals = [jax.device_put(v, rep) for v in self._p_vals]
        self._aux_vals = [jax.device_put(v, rep) for v in self._aux_vals]
        self._s_tree = tu.tree_map(lambda v, sh: jax.device_put(v, sh),
                                   self._s_tree, s_shardings)
        self._shard_plan = plan
        self._rep_sharding = rep
        self._s_shardings = s_shardings
        # per-chunk collective payload estimate (ring convention, see
        # docs/sharding.md): params whose state spec actually shards
        n = plan.num_shards
        sharded_bytes = sum(
            int(np.prod(w.shape)) * w.dtype.itemsize
            for w, sh in zip(weights, w_shardings)
            if any(ax is not None for ax in sh.spec))
        self._collective_bytes_per_step = \
            int(sharded_bytes * (n - 1) / float(n)) if n > 1 else 0

        def pin(new_p, new_s, aux_new):
            wsc = jax.lax.with_sharding_constraint
            new_p = [wsc(a, rep) for a in new_p]
            new_s = tu.tree_map(lambda a, sh: wsc(a, sh), new_s,
                                s_shardings)
            aux_new = [wsc(a, rep) for a in aux_new]
            return new_p, new_s, aux_new

        self._carry_pin = pin

    def sharding_info(self) -> Optional[Dict[str, Any]]:
        """Live carry placement: plan, total state bytes, and the
        per-device state bytes (the ZeRO-1 1/N memory win, measurable
        on the virtual CPU mesh and on real chips alike).  None when
        the carry is unsharded."""
        if self._shard_plan is None:
            return None
        import jax

        leaves = [l for l in jax.tree_util.tree_leaves(self._s_tree)]
        total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                    for l in leaves)
        per_dev: Dict[str, int] = {}
        for leaf in leaves:
            for sh in leaf.addressable_shards:
                key = str(sh.device.id)
                per_dev[key] = per_dev.get(key, 0) + int(
                    np.prod(sh.data.shape)) * leaf.dtype.itemsize
        return {"plan": self._shard_plan.describe(),
                "state_total_bytes": total,
                "state_bytes_per_device": per_dev}

    def _make_program(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from . import amp as _amp

        ex = self._exec
        n_args = len(self._arg_names)
        diff_idx, data_idx, fixed_idx = (self._diff_idx, self._data_idx,
                                         self._fixed_idx)
        with _amp.scope(ex._amp_dtype):
            train_fn = _build_graph_fn(ex._symbol, ex._arg_names,
                                       ex._aux_names, is_train=True)
        step = self._scan_step.step
        collect = self._collect
        guard_on = self._guard is not None
        track_health = self._track_health
        stats_on = self._stats_on
        carry_pin = self._carry_pin

        def program(p_vals, s_tree, aux_vals, fixed_vals, base_key, t0,
                    data_stack, lr_rows):
            def body(carry, xs):
                p, s, aux, t = carry
                data_vals, lr_row = xs
                key = jax.random.fold_in(base_key, t)

                def f(pv):
                    full = [None] * n_args
                    for j, i in enumerate(diff_idx):
                        full[i] = pv[j]
                    for j, i in enumerate(fixed_idx):
                        full[i] = fixed_vals[j]
                    for j, i in enumerate(data_idx):
                        full[i] = data_vals[j]
                    return train_fn(full, aux, key)

                (outs, aux_new), vjp = jax.vjp(f, p)
                ones = [jnp.ones_like(o) for o in outs]
                zaux = [jnp.zeros_like(a) for a in aux_new]
                (grads,) = vjp((ones, zaux))
                new_p, new_s = step(p, s, grads, lr_row)
                if track_health:
                    # in-graph grad health: finiteness + global l2 norm
                    # in the same fused reductions (a norm overflow is
                    # folded into the flag so isfinite(sq) can't mask a
                    # per-element NaN)
                    sq = jnp.float32(0.0)
                    ok = jnp.bool_(True)
                    if stats_on:
                        lnorms = []
                    for g in grads:
                        g32 = g.astype(jnp.float32)
                        gsq = jnp.sum(jnp.square(g32))
                        sq = sq + gsq
                        ok = ok & jnp.isfinite(g32).all()
                        if stats_on:
                            lnorms.append(jnp.sqrt(gsq))
                    ok = ok & jnp.isfinite(sq)
                    if guard_on:
                        # non-finite step: keep params, opt state AND
                        # aux (a blown-up forward poisons BN stats too)
                        new_p = [jnp.where(ok, a, b)
                                 for a, b in zip(new_p, p)]
                        new_s = jax.tree_util.tree_map(
                            lambda a, b: jnp.where(ok, a, b), new_s, s)
                        aux_new = [jnp.where(ok, a, b)
                                   for a, b in zip(aux_new, aux)]
                    ys = {"outs": tuple(outs) if collect else (),
                          "bad": ~ok, "gnorm": jnp.sqrt(sq)}
                    if stats_on:
                        ys["lnorms"] = tuple(lnorms)
                else:
                    ys = tuple(outs) if collect else ()
                if carry_pin is not None:
                    # sharded-carry mode: params/aux pinned replicated,
                    # opt state pinned to its ZeRO-1 placement, every
                    # scan iteration — GSPMD keeps the forward
                    # replicated and the update sharded
                    new_p, new_s, aux_new = carry_pin(new_p, new_s,
                                                      aux_new)
                return (new_p, new_s, aux_new, t + 1), ys

            (p, s, aux, _), outs = lax.scan(
                body, (p_vals, s_tree, aux_vals, t0),
                (data_stack, lr_rows), unroll=self._unroll)
            return p, s, aux, outs

        return program

    # -- data staging -----------------------------------------------------
    def stack_batches(self, batches: Sequence[Any]):
        """Stack K DataBatches into per-slot (K, ...) arrays (host-side;
        ONE transfer per slot when the program runs)."""
        import jax.numpy as jnp

        if len(batches) != self._K:
            raise MXNetError("expected %d batches, got %d"
                             % (self._K, len(batches)))
        mod = self._module
        stacks = []
        for j, i in enumerate(self._data_idx):
            name = self._arg_names[i]
            if name in mod._data_names:
                slot = mod._data_names.index(name)
                vals = [b.data[slot] for b in batches]
            else:
                slot = mod._label_names.index(name)
                vals = [b.label[slot] for b in batches]
            want = self._exec.arg_arrays[i].dtype
            parts = []
            for v in vals:
                arr = v._data if isinstance(v, NDArray) else jnp.asarray(v)
                parts.append(arr.astype(want) if arr.dtype != want else arr)
            stacks.append(jnp.stack(parts))
        return stacks

    def _program_args(self, data_stack, base_key):
        """The full positional argument tuple `_jit_program` takes —
        single source of truth shared by run_stacked (execute) and
        lower_stacked (AOT analysis) so the two can't drift."""
        import jax.numpy as jnp

        lr_rows = self._scan_step.host_sched(self._K)
        fixed_vals = [self._exec.arg_arrays[i]._data
                      for i in self._fixed_idx]
        t0 = jnp.int32(self._t)
        lr_arr = jnp.asarray(lr_rows)
        if self._shard_plan is not None:
            # sharded-carry mode: every non-carry input rides the mesh
            # replicated (the carry was placed at init; jit propagates
            # from there)
            import jax

            rep = self._rep_sharding
            data_stack = [jax.device_put(d, rep) for d in data_stack]
            fixed_vals = [jax.device_put(v, rep) for v in fixed_vals]
            base_key = jax.device_put(base_key, rep)
            t0 = jax.device_put(t0, rep)
            lr_arr = jax.device_put(lr_arr, rep)
        return (self._p_vals, self._s_tree, self._aux_vals, fixed_vals,
                base_key, t0, data_stack, lr_arr)

    def lower_stacked(self, data_stack: List[Any]):
        """AOT-lower the fused K-step program for a staged stack
        (`jax.jit(...).lower`) without executing it.  `.compile()` the
        result for optimized-HLO text / cost / memory analysis — this
        is what `tools/hlo_report.py` uses for static attribution."""
        import jax

        return self._jit_program.lower(
            *self._program_args(data_stack, jax.random.PRNGKey(0)))

    # -- execution --------------------------------------------------------
    def run_stacked(self, data_stack: List[Any]):
        """Run K fused steps over pre-staged (K, ...) slot arrays.
        Returns stacked outputs (list of (K, ...) NDArrays) when
        collect_outputs, else None."""
        import time as _time

        import jax

        from . import random as _rnd
        from . import telemetry as _tel

        from . import compile_cache as _cc
        from . import inspect as _insp_mod

        K = self._K
        t_base = self._t
        base_key = _rnd._next_key() if self._exec._has_rng \
            else jax.random.PRNGKey(0)
        tok = _insp_mod.track_compile(
            self._insp, self._seen_sigs, "fused_train", "fused_train",
            "train", _cc.sig_of(data_stack),
            arg_names=[self._arg_names[i] for i in self._data_idx])
        prog_args = self._program_args(data_stack, base_key)
        t0 = _time.monotonic()
        pt0 = _perf.begin()
        with _OOM_RUN:
            p, s, aux, outs = self._jit_program(*prog_args)
        if tok is not None:
            tok.done(self._jit_program, prog_args)
        # block target = the new params: produced LAST in the scanned
        # program, so call->ready spans the full K-step chunk
        _perf.end(self._insp.name, "fused_train", pt0, outputs=p, n=K)
        bad_flags = gnorms = lnorms = prev_health = None
        if self._track_health:
            bad_dev, gn_dev = outs["bad"], outs["gnorm"]
            lnorms = outs.get("lnorms")
            outs = outs["outs"]
            if self._guard is not None:
                # guard armed: the skip/abort contract needs the flags
                # NOW (synchronous read — the PR 2 behavior)
                bad_flags = np.asarray(bad_dev)
                gnorms = np.asarray(gn_dev)
            else:
                # guard off: defer the host read one chunk — by the
                # next run these scalars are long since materialized,
                # so the loop never stalls on its own health check.
                # The batch stacks are held ONLY while a diagnosis
                # could still run (bounded by MXTPU_HEALTH_MAX_DIAG).
                prev_health = self._pending_health
                self._pending_health = (
                    t_base, base_key,
                    data_stack if _health.want_context() else None,
                    bad_dev, gn_dev)
        self._p_vals, self._s_tree, self._aux_vals = p, s, aux
        self._t += K
        self._optimizer.commit_scan_steps(self._opt_indices, K)
        if self._shard_plan is not None \
                and self._collective_bytes_per_step:
            # the ring-payload estimate of what GSPMD moved for the K
            # sharded updates (reduce-scatter grads in, allgather
            # params out) — same counters the eager ZeRO-1 engine ticks
            from . import profiler as _prof

            _prof.inc_stat("reduce_scatter_bytes",
                           self._collective_bytes_per_step * K)
            _prof.inc_stat("allgather_bytes",
                           self._collective_bytes_per_step * K)
        self._publish()
        # one record for the whole K-step program: per-step batch size
        # is the second dim of the staged (K, batch, ...) stacks
        batch = int(data_stack[0].shape[1]) \
            if data_stack and getattr(data_stack[0], "ndim", 0) > 1 else 0
        skipped_n = int(bad_flags.sum()) if bad_flags is not None else None
        _tel.record_step(batch_size=batch, n=K,
                         duration=_time.monotonic() - t0,
                         site="fused_train", skipped_n=skipped_n,
                         grad_norm=float(gnorms[-1])
                         if gnorms is not None else None)
        if self._stats_on and lnorms is not None:
            self._maybe_emit_stats(lnorms)
        if bad_flags is not None:
            # state is already published (skipped steps kept the old
            # buffers in-program); blame the FIRST bad step, then
            # account per-step health and abort on too many
            # CONSECUTIVE skips
            if bad_flags.any():
                k = int(np.argmax(bad_flags))
                _health.on_nonfinite(
                    "fused_train", gnorm=float(gnorms[k]),
                    ctx=self._diag_ctx(data_stack, base_key, t_base, k))
            for gn, bad in zip(gnorms, bad_flags):
                if not bad:
                    _health.observe_grad_norm(float(gn))
            for bad in bad_flags:
                self._guard.record(not bool(bad))
        elif prev_health is not None:
            self._check_pending(prev_health)
        # mx.checkpoint boundary: the end of a K-step chunk is the only
        # point where host copies of params/opt-state are coherent, so
        # periodic snapshots and SIGTERM flushes both anchor here
        if _ckpt.active():
            _ckpt.on_boundary(self._t)
        # mx.xprof auto-profile cadence (MXTPU_XPROF_EVERY, default
        # off): when disarmed this is two int/bool checks per chunk
        _xprof.maybe_autoprofile(self, data_stack)
        if self._collect:
            ctx = self._exec._ctx
            return [NDArray(o, ctx=ctx, _committed=True) for o in outs]
        return None

    # -- health hooks -----------------------------------------------------
    def _diag_ctx(self, data_stack, base_key, t_base: int, k: int):
        """Diagnosis context for scanned step ``k`` of a chunk: the
        exact batch slice and RNG key that step saw, with the CURRENT
        params/aux standing in for the mid-scan values (donation
        consumed those; with the guard on, skipped steps kept the
        pre-divergence buffers, so the stand-in is close)."""
        import jax

        ex = self._exec
        full = [None] * len(self._arg_names)
        for j, i in enumerate(self._diff_idx):
            full[i] = self._p_vals[j]
        for i in self._fixed_idx:
            full[i] = ex.arg_arrays[i]
        for j, i in enumerate(self._data_idx):
            full[i] = data_stack[j][k]
        key = jax.random.fold_in(base_key, t_base + k)
        return ("fused_train", ex._symbol, self._arg_names,
                ex._aux_names, full, list(ex.aux_arrays), key,
                ex._amp_dtype)

    def _check_pending(self, pending) -> None:
        """Read the PREVIOUS chunk's deferred health scalars (ready by
        now — their program finished before this chunk dispatched)."""
        t_base, base_key, stack, bad_dev, gn_dev = pending
        try:
            bad = np.asarray(bad_dev)
            gn = np.asarray(gn_dev)
        except Exception:
            return
        if bad.any():
            k = int(np.argmax(bad))
            ctx = self._diag_ctx(stack, base_key, t_base, k) \
                if stack is not None else None
            _health.on_nonfinite("fused_train", gnorm=float(gn[k]),
                                 ctx=ctx)
        else:
            for v in gn:
                _health.observe_grad_norm(float(v))

    def _maybe_emit_stats(self, lnorms) -> None:
        """Opt-in per-layer stat streaming on the
        ``MXTPU_HEALTH_STATS_EVERY`` cadence (counted in CHUNKS — each
        run is K wall steps): grad norms come from the scanned program
        (last step of the chunk), param norms from one fused reduction
        over the published params."""
        n = _health.stats_every()
        if n <= 0:
            return
        self._stats_count += 1
        if self._stats_count % n:
            return
        names = [self._arg_names[i] for i in self._diff_idx]
        pn = _health.layer_norms(self._p_vals)
        try:
            opt = self._optimizer
            lr = opt.lr if opt.lr_scheduler is None \
                else opt.lr_scheduler(opt.num_update)
            scale = abs(float(lr) * float(opt.rescale_grad))
        except Exception:
            scale = 1.0
        _health.emit_stats(names, pn, [l[-1] for l in lnorms],
                           scale=scale, site="fused_train")

    def run(self, batches: Sequence[Any]):
        """Stage K DataBatches and run them as one program."""
        return self.run_stacked(self.stack_batches(batches))

    def _publish(self):
        """Point the executor/updater NDArrays at the freshest device
        buffers (host pointer swap — no transfer)."""
        ex = self._exec
        for j, i in enumerate(self._diff_idx):
            ex.arg_arrays[i]._set_jax(self._p_vals[j])
        for arr, val in zip(ex.aux_arrays, self._aux_vals):
            arr._set_jax(val)
        self._scan_step.writeback_states(self._state_objs, self._s_tree)
        self._module._params_dirty = True

    def finalize(self):
        """Alias kept for symmetry with reference Trainer APIs; state is
        already published after every run().  Also drains the deferred
        health read so the LAST chunk's non-finite steps still get
        blamed."""
        pending, self._pending_health = self._pending_health, None
        if pending is not None:
            self._check_pending(pending)
        self._publish()
