"""ShardingPlan — the one partitioning decision the whole stack consumes.

A plan names the mesh axes (data axis for replica sharding, model axis
for tensor parallelism), carries per-parameter ``PartitionSpec``
overrides, and decides — once — whether optimizer state is sharded
across data-parallel replicas (ZeRO-1, arXiv 2004.13336: shard the
Adam moments and the weight update over the replicas, allgather the
updated params).  Trainer / Module / FusedTrainLoop / kvstore=tpu /
``mxtpu.parallel`` all resolve their partitioning through the ACTIVE
plan instead of hand-wiring collectives per call site; the ``shard``
pass (`mxtpu/passes/sharding.py`) stamps the same decision onto the
Symbol graph so provenance rides `mx.inspect` program records.

Two execution modes share one plan object:

  * **host-replica** (Module/Trainer over a context list): ``num_shards``
    is the replica count; :meth:`shard_dim` / :meth:`shard_slice` drive
    the eager ZeRO-1 updater (`mxtpu/sharding/zero1.py`).
  * **SPMD** (a live `jax.sharding.Mesh`): :meth:`spec_for` /
    :meth:`opt_state_spec` hand out ``PartitionSpec``s, and
    :meth:`named_sharding` the `NamedSharding`s GSPMD consumes
    (FusedTrainLoop's scanned carry, `parallel/transformer.py`).

Env knobs (docs/env_vars.md):
  ``MXTPU_SHARD``            ``zero1``/``1``: Trainer/Module auto-build a
                             plan over their contexts when none is active
  ``MXTPU_SHARD_OPT_STATE``  default ``1``: optimizer-state sharding on
                             by default inside an active plan
  ``MXTPU_SHARD_MIN_SIZE``   default ``4096``: min param elements worth a
                             per-step collective (tiny LayerNorm vectors
                             keep replicated state)
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, getenv

__all__ = ["ShardingPlan", "current_plan", "plan_scope", "auto_plan",
           "shard_requested", "default_min_shard_elems",
           "opt_state_sharding_default"]

_state = threading.local()


def default_min_shard_elems() -> int:
    """MXTPU_SHARD_MIN_SIZE — smallest parameter (in elements) whose
    optimizer state is worth sharding (matches the transformer stack's
    historical ``_ZERO1_MIN_ELEMS``)."""
    v = getenv("MXTPU_SHARD_MIN_SIZE")
    return int(v) if v else 4096


def opt_state_sharding_default() -> bool:
    """MXTPU_SHARD_OPT_STATE — ZeRO-1 state sharding default (ON)."""
    return (getenv("MXTPU_SHARD_OPT_STATE") or "1").lower() \
        not in ("0", "off", "false", "none")


class ShardingPlan(object):
    """One partitioning decision: axes, per-param specs, ZeRO-1 on/off.

    Parameters
    ----------
    num_shards : int, optional
        Data-parallel replica count for host-replica mode.  Defaults to
        the mesh's ``data_axis`` size when a mesh is given, else 1; a
        plan built with neither resolves when Trainer/Module engage it
        (:meth:`resolved`).
    mesh : jax.sharding.Mesh, optional
        The SPMD device mesh (None = host-replica mode).
    data_axis / model_axis : str
        Mesh axis names for replica and tensor parallelism.
    param_specs : dict name -> PartitionSpec, optional
        Model-parallel placement overrides; params absent here are
        replicated (their state sharding is pure ZeRO-1).
    shard_optimizer_state : bool, optional
        ZeRO-1 on/off; defaults to ``MXTPU_SHARD_OPT_STATE`` (on).
    shard_data : bool
        SPMD mode only: shard the batch dim of data inputs over
        ``data_axis`` (off by default so replicated-data parity runs
        stay bitwise).
    data_names : sequence of str
        Variable names treated as data/labels by :meth:`spec_for`.
    min_shard_elems : int, optional
        Per-param element floor below which state stays replicated.
    """

    def __init__(self, num_shards: Optional[int] = None, mesh=None,
                 data_axis: str = "dp", model_axis: str = "tp",
                 param_specs: Optional[Dict[str, Any]] = None,
                 shard_optimizer_state: Optional[bool] = None,
                 shard_data: bool = False,
                 data_names: Sequence[str] = ("data", "softmax_label"),
                 min_shard_elems: Optional[int] = None,
                 name: Optional[str] = None):
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.param_specs = dict(param_specs or {})
        self.shard_data = bool(shard_data)
        self.data_names = tuple(data_names)
        self.min_shard_elems = (default_min_shard_elems()
                                if min_shard_elems is None
                                else int(min_shard_elems))
        self.shard_optimizer_state = (opt_state_sharding_default()
                                      if shard_optimizer_state is None
                                      else bool(shard_optimizer_state))
        self.name = name
        if num_shards is None and mesh is not None:
            num_shards = int(mesh.shape.get(data_axis, 1))
        self._num_shards = None if num_shards is None else int(num_shards)

    # -- sizing ----------------------------------------------------------
    @property
    def num_shards(self) -> int:
        """Replica count; 1 when still unresolved."""
        return self._num_shards if self._num_shards is not None else 1

    @property
    def resolved_explicitly(self) -> bool:
        return self._num_shards is not None

    def resolved(self, num_shards: int) -> "ShardingPlan":
        """This plan bound to a concrete replica count: returns self
        when it already matches (or was never pinned — then a pinned
        copy), raises on a conflicting pin."""
        num_shards = int(num_shards)
        if self._num_shards is None:
            import copy

            out = copy.copy(self)
            out._num_shards = num_shards
            return out
        if self._num_shards != num_shards:
            raise MXNetError(
                "ShardingPlan pinned to %d shards cannot drive %d "
                "replicas" % (self._num_shards, num_shards))
        return self

    # -- ZeRO-1 placement -------------------------------------------------
    def shard_dim(self, name: str, shape: Sequence[int]) -> Optional[int]:
        """The dimension to shard ``name``'s optimizer state over the
        data axis: the first dim NOT claimed by the param's model spec
        whose size divides ``num_shards``.  None = state stays
        replicated (plan off for this param: too small, indivisible, or
        ZeRO-1 disabled)."""
        n = self.num_shards
        if n <= 1 or not self.shard_optimizer_state:
            return None
        shape = tuple(int(s) for s in shape)
        if int(np.prod(shape)) < self.min_shard_elems:
            return None
        spec = self.param_specs.get(name, ())
        for i, size in enumerate(shape):
            ax = spec[i] if i < len(spec) else None
            if ax is None and size % n == 0:
                return i
        return None

    def shard_slice(self, shape: Sequence[int], dim: int,
                    rank: int) -> Tuple[slice, ...]:
        """Index tuple selecting replica ``rank``'s 1/N chunk of a
        buffer of ``shape`` along ``dim``."""
        n = self.num_shards
        if not 0 <= rank < n:
            raise MXNetError("rank %d out of range for %d shards"
                             % (rank, n))
        size = int(shape[dim])
        step = size // n
        idx = [slice(None)] * len(shape)
        idx[dim] = slice(rank * step, (rank + 1) * step)
        return tuple(idx)

    # -- SPMD specs -------------------------------------------------------
    def spec_for(self, name: str, shape: Optional[Sequence[int]] = None):
        """PartitionSpec for variable ``name``: the model-parallel
        override when one exists, batch-sharded over the data axis for
        data/label inputs (only with ``shard_data``), replicated
        otherwise."""
        from jax.sharding import PartitionSpec as P

        if name in self.param_specs:
            return self.param_specs[name]
        if name in self.data_names and self.shard_data \
                and self.num_shards > 1:
            return P(self.data_axis)
        return P()

    def opt_state_spec(self, name: str, shape: Sequence[int]):
        """PartitionSpec for ``name``'s optimizer state: the param spec
        with the data axis added on :meth:`shard_dim` — the ZeRO-1
        placement (arXiv 2004.13336)."""
        from jax.sharding import PartitionSpec as P

        base = self.param_specs.get(name, ())
        spec = list(base) + [None] * (len(shape) - len(base))
        dim = self.shard_dim(name, shape)
        if dim is not None:
            spec[dim] = self.data_axis
        return P(*spec)

    def named_sharding(self, spec):
        """NamedSharding over this plan's mesh (SPMD mode only)."""
        if self.mesh is None:
            raise MXNetError("plan has no mesh: named_sharding is for "
                             "SPMD plans")
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    # -- identity / provenance -------------------------------------------
    def describe(self) -> str:
        """Compact provenance string for pass reports, inspect records
        and telemetry compile events."""
        mode = "zero1" if self.shard_optimizer_state else "repl"
        parts = ["%s:n=%d" % (mode, self.num_shards),
                 "axis=%s" % self.data_axis]
        if self.mesh is not None:
            parts.append("mesh=%s" % "x".join(
                str(s) for s in self.mesh.devices.shape))
        if self.shard_data:
            parts.append("data")
        if self.param_specs:
            parts.append("mp=%d" % len(self.param_specs))
        if self.name:
            parts.insert(0, self.name)
        return ",".join(parts)

    def __repr__(self):
        return "<ShardingPlan %s>" % self.describe()

    # -- activation -------------------------------------------------------
    def activate(self):
        """``with plan.activate():`` — make this the current plan for
        the block (same stack discipline as `MeshContext`)."""
        return plan_scope(self)


class plan_scope(object):
    """``with plan_scope(plan):`` — push ``plan`` onto the thread's
    current-plan stack.  ``plan_scope(None)`` masks any outer plan."""

    def __init__(self, plan: Optional[ShardingPlan]):
        self._plan = plan

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = []
        _state.stack.append(self._plan)
        return self._plan

    def __exit__(self, *exc):
        _state.stack.pop()
        return False


def current_plan() -> Optional[ShardingPlan]:
    """Innermost active plan (None when no scope is live).  When no
    scope was ever entered, ``MXTPU_SHARD=zero1|1`` yields a process
    default plan (unpinned; Trainer/Module resolve the replica count)."""
    stack = getattr(_state, "stack", None)
    if stack:
        return stack[-1]
    if (getenv("MXTPU_SHARD") or "").lower() in ("1", "zero1", "on"):
        global _ENV_PLAN
        if _ENV_PLAN is None:
            _ENV_PLAN = ShardingPlan(name="env")
        return _ENV_PLAN
    return None


_ENV_PLAN: Optional[ShardingPlan] = None


def shard_requested() -> bool:
    """True when an active plan could shard anything — how the pass
    manager decides whether ``shard`` joins the default pass set."""
    return current_plan() is not None


def auto_plan(num_shards: Optional[int] = None, mesh=None,
              **kwargs) -> ShardingPlan:
    """Convenience: a ZeRO-1 plan over ``num_shards`` replicas (or a
    mesh's data axis)."""
    return ShardingPlan(num_shards=num_shards, mesh=mesh, **kwargs)
