"""mx.shard — the sharding-aware distributed backbone.

One `ShardingPlan` (data/model axes, per-param PartitionSpecs,
optimizer-state sharding ON by default) is chosen once — by
Trainer/Module, a `with plan.activate():` scope, or ``MXTPU_SHARD=zero1``
— and consumed everywhere:

  * `gluon.Trainer` / `Module` replace their N redundant per-replica
    updaters with ONE :class:`ZeRO1Updater` holding each param's Adam
    state in N disjoint chunks (arXiv 2004.13336): slice the merged
    grad (reduce-scatter), update the chunk, allgather the params.
  * `FusedTrainLoop` shards its scanned opt-state carry over the
    plan's mesh (GSPMD compiles the same reduce-scatter/allgather
    into the K-step program).
  * ``kvstore=tpu`` and `mxtpu.parallel` resolve their collective
    axis/mesh from the plan instead of hand-wired call sites.
  * the ``shard`` graph pass (`mxtpu/passes/sharding.py`) stamps the
    decision onto the Symbol graph — provenance on `mx.inspect`
    program records and telemetry ``compile`` events.
  * :func:`reshard` moves params/state between two plans' layouts
    (train<->serve, arXiv 2112.01075) in one device_put per leaf.

See `docs/sharding.md` for the workflow, `tools/check_sharding.py`
(tier-1) for the parity + memory contract, and
`benchmark/python/bench_sharding.py` for the scaling seed.
"""
from __future__ import annotations

from .plan import (ShardingPlan, auto_plan, current_plan,
                   default_min_shard_elems, opt_state_sharding_default,
                   plan_scope, shard_requested)
from .zero1 import ZeRO1Updater, hbm_report, state_nbytes, tree_nbytes
from .reshard import reshard

__all__ = [
    "ShardingPlan", "ZeRO1Updater", "auto_plan", "current_plan",
    "default_min_shard_elems", "hbm_report",
    "opt_state_sharding_default", "plan_scope", "reshard",
    "shard_requested", "state_nbytes", "tree_nbytes",
]
