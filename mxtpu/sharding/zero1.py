"""ZeRO-1 sharded optimizer updates for host-replica data parallelism.

The redundancy being removed (ROADMAP item 1): a Module/Trainer over N
device contexts used to build N full `Updater`s — every replica held a
complete copy of the Adam moments and re-ran the identical whole-tree
update.  :class:`ZeRO1Updater` replaces them with ONE updater that
owns each parameter's state in N disjoint 1/N chunks (arXiv
2004.13336, cross-replica weight-update sharding):

  1. the merged gradient (the kvstore already all-reduced it) is
     SLICED per replica rank — semantically the reduce-scatter half of
     an all-reduce;
  2. rank r applies the optimizer to its chunk only, against the ONE
     state shard that exists for that chunk (ZeRO-1: optimizer state
     lives nowhere else);
  3. the updated chunks are concatenated and broadcast back into every
     replica's weight — the allgather half.

Because every supported optimizer's update is ELEMENTWISE, slicing
changes memory, not math: the sharded trajectory is bitwise identical
to the replicated one (asserted by `tools/check_sharding.py`, tier-1).
Optimizers whose update is NOT a pure elementwise function of
(weight, grad, state) — LARS-style norm scaling, per-call noise or
schedule scalars — declare ``zero1_compatible = False`` and keep the
replicated path.

Params below the plan's ``min_shard_elems`` floor (or with no dim
divisible by N) keep ONE full state copy here (still an N-fold saving
over the per-replica updaters) and a plain broadcast.

Checkpoint contract: :meth:`ZeRO1Updater.get_states` GATHERS the
shards into full host buffers and emits the exact wire format of
`optimizer.Updater.get_states`, so a checkpoint saved sharded loads
into any replica count — including 1 (a plain Updater) — and
:meth:`set_states` re-shards full states under the active plan.

Per-step collective payloads land in ``profiler.stats()`` as
``allgather_bytes`` / ``reduce_scatter_bytes`` (the ring-algorithm
per-replica payload, ``(n-1)/n * bytes``, same convention as
`parallel/collectives.microbench`).
"""
from __future__ import annotations

import functools
import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt_mod
from .plan import ShardingPlan

__all__ = ["ZeRO1Updater", "tree_nbytes", "state_nbytes",
           "hbm_report"]


@functools.lru_cache(maxsize=4096)
def _fused_apply_plan(plan_key: Tuple[Tuple[str, Tuple], ...]):
    """Jitted executor for one rank's captured optimizer applies:
    ``plan_key`` is ``((op_name, canonical_attrs), ...)`` and the
    returned callable maps ``(per-op (weight, grad, *states) jax
    arrays, ...)`` to a matching tuple of output tuples.  Each op body
    is built exactly like the eager cache builds it (`ops.registry.
    _jitted`: ``partial(op.fn, **attrs)`` with scalar attrs baked as
    constants) so every per-param subgraph — and therefore every
    result — is bitwise identical to the eager dispatch; only the
    dispatch count changes.  Optimizers with a per-step attr (Adam's
    bias-corrected lr) key a new plan per step, the same retrace the
    eager cache pays; the lru bound keeps both from growing without
    limit."""
    import jax

    from ..ops.registry import get_op

    bodies = [functools.partial(get_op(name).fn, **dict(attrs_key))
              for name, attrs_key in plan_key]

    def step(arg_lists):
        outs = []
        for body, args in zip(bodies, arg_lists):
            res = body(*args)
            outs.append(res if isinstance(res, tuple) else (res,))
        return tuple(outs)

    return jax.jit(step)


def tree_nbytes(obj) -> int:
    """Total payload bytes of a (possibly nested) optimizer-state
    object: NDArrays, jax arrays, tuples/lists/dicts thereof."""
    if obj is None:
        return 0
    if isinstance(obj, NDArray):
        return int(obj.size) * obj.dtype.itemsize
    if isinstance(obj, (tuple, list)):
        return sum(tree_nbytes(o) for o in obj)
    if isinstance(obj, dict):
        return sum(tree_nbytes(o) for o in obj.values())
    if hasattr(obj, "nbytes"):
        return int(obj.nbytes)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        return int(np.prod(obj.shape)) * np.dtype(obj.dtype).itemsize
    return 0


def state_nbytes(updater) -> int:
    """Optimizer-state bytes held by an `optimizer.Updater` (or
    :class:`ZeRO1Updater`) — what `tools/check_sharding.py` measures."""
    return tree_nbytes(getattr(updater, "states", None))


def hbm_report(updater) -> Dict[str, Any]:
    """Measured ZeRO-1 memory ledger for ``updater``: full vs
    per-replica optimizer-state bytes (walked off the live state
    arrays) and the freed delta, joined with every registered
    program's STATIC shardable-pool line (``mx.hbm.plan()["what_if"]
    ["zero1_optimizer_state_bytes"]`` — freed under N shards is
    pool*(N-1)/N of that) so prediction and measurement sit side by
    side.  Plain `optimizer.Updater`s report freed=0."""
    full = state_nbytes(updater)
    per_replica = getattr(updater, "per_replica_state_nbytes",
                          lambda: full)()
    out: Dict[str, Any] = {
        "state_bytes_full": int(full),
        "state_bytes_per_replica": int(per_replica),
        "hbm_freed_bytes": max(0, int(full) - int(per_replica)),
        "n_shards": int(getattr(updater, "n", 1) or 1),
    }
    try:
        from .. import hbm as _hbm
        from .. import inspect as _insp

        predicted = {}
        with _insp._lock:
            records = list(_insp._REGISTRY.values())
        for rec in records:
            si = rec.latest_sig("train")
            if si is None or si._analysis is None:
                continue
            mp = _hbm.plan(rec, kind="train")
            wi = mp.get("what_if") if isinstance(mp, dict) else None
            if wi and wi.get("zero1_optimizer_state_bytes"):
                predicted[rec.name] = int(
                    wi["zero1_optimizer_state_bytes"])
        if predicted:
            out["predicted_zero1_shardable_bytes"] = predicted
    except Exception:
        pass
    return out


def _map_state(obj, fn):
    """Apply ``fn`` to every array leaf of a state object, preserving
    the (None / NDArray / nested tuple) structure create_state uses."""
    if obj is None:
        return None
    if isinstance(obj, NDArray):
        return fn(obj)
    if isinstance(obj, (tuple, list)):
        return tuple(_map_state(o, fn) for o in obj)
    raise MXNetError("unsupported optimizer state leaf %r" % type(obj))


def _zip_states(objs, fn):
    """Leafwise combine of same-structure state objects (the gather)."""
    first = objs[0]
    if first is None:
        return None
    if isinstance(first, NDArray):
        return fn(objs)
    if isinstance(first, (tuple, list)):
        return tuple(_zip_states([o[i] for o in objs], fn)
                     for i in range(len(first)))
    raise MXNetError("unsupported optimizer state leaf %r" % type(first))


class ZeRO1Updater(object):
    """One updater for ALL replicas: sharded state, sliced updates,
    allgathered params.  Duck-types `optimizer.Updater` (``states``,
    ``get_states``/``set_states``, ``update_multi``) so Module/Trainer
    checkpointing and `kvstore.set_updater` work unchanged."""

    def __init__(self, optimizer: opt_mod.Optimizer, plan: ShardingPlan,
                 idx2name: Optional[Dict[Any, str]] = None):
        if not getattr(optimizer, "zero1_compatible", True):
            raise MXNetError(
                "optimizer %s is not ZeRO-1 compatible (non-elementwise "
                "update); use the replicated path"
                % type(optimizer).__name__)
        if plan.num_shards < 1:
            raise MXNetError("plan resolves to %d shards"
                             % plan.num_shards)
        self.optimizer = optimizer
        self.plan = plan
        self.n = plan.num_shards
        self.idx2name = dict(idx2name or {})
        # index -> per-rank state shards (list, sharded params) or the
        # one full state (unsharded params).  `shard_dims` records the
        # split dim; None = unsharded.
        self.states: Dict[Any, Any] = {}
        self.shard_dims: Dict[Any, Optional[int]] = {}
        self.states_synced: Dict[Any, bool] = {}

    # -- naming / placement ----------------------------------------------
    def _name_of(self, index) -> str:
        return self.idx2name.get(
            index, self.optimizer.idx2name.get(index, str(index)))

    def _dim_for(self, index, weight: NDArray) -> Optional[int]:
        dim = self.shard_dims.get(index, _MISSING)
        if dim is _MISSING:
            dim = self.plan.shard_dim(self._name_of(index), weight.shape)
            self.shard_dims[index] = dim
        return dim

    # -- state lifecycle --------------------------------------------------
    def _ensure_state(self, index, weight: NDArray) -> None:
        if index in self.states:
            return
        opt = self.optimizer
        dim = self._dim_for(index, weight)
        if dim is None:
            self.states[index] = opt.create_state_multi_precision(
                index, weight)
        else:
            shards = []
            for r in range(self.n):
                w_sl = NDArray(
                    weight._data[self.plan.shard_slice(weight.shape,
                                                       dim, r)],
                    ctx=weight.ctx, _committed=True)
                shards.append(opt.create_state_multi_precision(index,
                                                               w_sl))
            self.states[index] = shards
        self.states_synced[index] = True

    def state_nbytes(self) -> int:
        """Bytes of optimizer state THIS updater holds (all shards —
        divide by ``n`` for the per-replica figure on real hardware,
        where each rank materializes only its own chunk)."""
        return tree_nbytes(self.states)

    def per_replica_state_nbytes(self) -> int:
        """Optimizer-state bytes a single replica owns under this
        plan: its 1/N chunk of every sharded param plus a full copy of
        each unsharded (replicated-state) param."""
        total = 0
        for index, st in self.states.items():
            if self.shard_dims.get(index) is None:
                total += tree_nbytes(st)
            else:
                total += tree_nbytes(st[0])
        return total

    def hbm_freed_bytes(self) -> int:
        """MEASURED per-replica HBM this plan frees vs unsharded
        replication: full-state bytes minus the bytes one replica
        actually owns (both walked off the live state arrays, not
        estimated).  The figure `mx.hbm`'s what-if ZeRO-1 line is
        checked against."""
        return max(0, self.state_nbytes()
                   - self.per_replica_state_nbytes())

    # -- update -----------------------------------------------------------
    def update_replicas(self, triples: List[Tuple[Any, List[NDArray],
                                                  List[NDArray]]],
                        pre_reduced: bool = True) -> None:
        """Apply one optimizer step for every parameter across all
        replicas.  ``triples`` is ``[(index, grad_replicas,
        weight_replicas), ...]``.  ``pre_reduced=True`` (the kvstore
        path) means the grad replicas already hold the merged sum;
        False makes this updater sum them first (the reduce half of
        the reduce-scatter).  Weights of every replica are left
        identical after the call.

        Dense sharded params are updated in ONE jitted program per
        rank (`_update_batched` capture-and-replay over the whole
        rank-r slice tree) instead of one eager dispatch per
        (param, rank) — the dispatch-bound hot spot on small-param
        trees (ROADMAP item 3).  Batching changes dispatch count, not
        math: each param's subgraph is the optimizer's own `_apply`
        op built exactly as the eager cache builds it (bitwise parity
        asserted by tests/test_sharding.py and
        tools/check_sharding.py).  Params the batched path cannot
        take (sparse grads, unsharded state, optimizers without
        `single_apply_update`) keep the per-param path."""
        from .. import profiler as _prof
        from ..ndarray.sparse import BaseSparseNDArray

        batchable = []  # (index, merged grad, w0, weight replicas)
        for index, grads, weights in triples:
            g0, w0 = grads[0], weights[0]
            if isinstance(g0, BaseSparseNDArray):
                self._update_one(index, grads, weights, _prof,
                                 pre_reduced)
                continue
            if not pre_reduced and len(grads) > 1:
                from ..kvstore import _fused_sum

                g0 = NDArray(_fused_sum([g._data for g in grads]),
                             ctx=g0.ctx, _committed=True)
            if self._dim_for(index, w0) is None or \
                    self.shard_dims.get(index) is None:
                self._update_one(index, [g0], weights, _prof, True)
                continue
            batchable.append((index, g0, w0, weights))
        if not batchable:
            return
        if len(batchable) == 1 or \
                not self._update_batched(batchable, _prof):
            # one param fuses nothing; a False fused_update_multi
            # (no fused form / mixed mp tree) mutated nothing yet
            for index, g0, w0, weights in batchable:
                self._update_one(index, [g0], weights, _prof, True)

    def _update_batched(self, items, _prof) -> bool:
        """One jitted program per RANK covering every dense sharded
        param's rank-r slice update, instead of one eager dispatch per
        (param, rank).

        Bitwise parity with the eager path is BY CONSTRUCTION, not by
        reimplementation: the optimizer's own ``update()`` runs with
        ``_apply`` shimmed to CAPTURE its single (op, attrs) call, and
        the batched program replays exactly those ops built the same
        way the eager cache builds them — ``functools.partial(op.fn,
        **attrs)`` with scalars (lr/wd/beta) baked as compile-time
        constants (see ``ops.registry._jitted``).  Passing scalars as
        jit *arguments* instead lets XLA constant-fold differently
        (~1 ulp/step drift), which is why this does not reuse
        ``fused_update_multi``.

        Returns False — with counters restored, nothing else mutated —
        when the optimizer cannot be captured (no
        ``single_apply_update`` declaration, or mp low-precision
        weights whose master-copy cast-back happens outside
        ``_apply``); the caller then falls back per-param."""
        import jax.numpy as jnp

        from ..optimizer.optimizer import _is_lowp
        from ..ops import registry as _reg

        opt = self.optimizer
        n = self.n
        if not getattr(opt, "single_apply_update", False):
            return False
        for index, _, w0, _ in items:
            self._ensure_state(index, w0)
        if opt.multi_precision and any(_is_lowp(it[2].dtype)
                                       for it in items):
            return False
        indices = [it[0] for it in items]
        counts_before = {i: opt._index_update_count.get(i)
                         for i in indices}

        def _rewind():
            # every rank applies the SAME logical step: restore the
            # counters so bias correction / schedules see one advance
            # per wall step no matter how many ranks ran
            for i in indices:
                cb = counts_before[i]
                if cb is None:
                    opt._index_update_count.pop(i, None)
                else:
                    opt._index_update_count[i] = cb

        new_slices: Dict[Any, list] = {i: [] for i in indices}
        for r in range(n):
            if r > 0:
                _rewind()
            w_sls, g_sls, st_r = [], [], []
            for index, g0, w0, _ in items:
                dim = self.shard_dims[index]
                idx = self.plan.shard_slice(w0.shape, dim, r)
                w_sls.append(NDArray(w0._data[idx], ctx=w0.ctx,
                                     _committed=True))
                g_sls.append(NDArray(g0._data[idx], ctx=g0.ctx,
                                     _committed=True))
                st_r.append(self.states[index][r])
            captured: list = []
            opt._apply = lambda op_name, weight, grad, states, **at: \
                captured.append((op_name, weight, grad,
                                 tuple(states), at))
            try:
                for (index, _, _, _), w_sl, g_sl, st in zip(
                        items, w_sls, g_sls, st_r):
                    opt.update_multi_precision(index, w_sl, g_sl, st)
            finally:
                del opt._apply  # restore the class staticmethod
            ok = len(captured) == len(items) and all(
                c[1] is w and not _reg.get_op(c[0]).needs_rng
                for c, w in zip(captured, w_sls))
            if not ok:
                # the update did eager math outside its one _apply
                # (contract violation of single_apply_update); only
                # the counters advanced, so undo them and fall back
                if r == 0:
                    _rewind()
                    return False
                raise MXNetError(
                    "zero1 batched update: optimizer %s captured "
                    "inconsistently across ranks" % type(opt).__name__)
            plan_key = tuple((c[0], _reg.canonical_attrs(c[4]))
                             for c in captured)
            outs = _fused_apply_plan(plan_key)(
                tuple(tuple([c[1]._data, c[2]._data]
                            + [s._data for s in c[3]])
                      for c in captured))
            for c, out in zip(captured, outs):
                c[1]._set_jax(out[0])
                for st, new in zip(c[3], out[1:]):
                    st._set_jax(new)
            for (index, _, _, _), w_sl in zip(items, w_sls):
                new_slices[index].append(w_sl._data)
        _prof.inc_stat("zero1_fused_rank_updates", n)
        ring = (n - 1) / float(n)
        for index, g0, w0, weights in items:
            dim = self.shard_dims[index]
            # allgather: chunks -> full param, broadcast to replicas
            full = jnp.concatenate(new_slices[index], axis=dim)
            w0._set_jax(full)
            self._broadcast(w0, weights[1:])
            nbytes = int(np.prod(w0.shape)) * w0.dtype.itemsize
            _prof.inc_stat("allgather_bytes", int(nbytes * ring))
            _prof.inc_stat("reduce_scatter_bytes",
                           int(g0.dtype.itemsize
                               * int(np.prod(g0.shape)) * ring))
        return True

    def _update_one(self, index, grads, weights, _prof,
                    pre_reduced: bool = True) -> None:
        import jax.numpy as jnp

        from ..ndarray.sparse import BaseSparseNDArray

        opt = self.optimizer
        w0, g0 = weights[0], grads[0]
        if not pre_reduced and len(grads) > 1:
            if isinstance(g0, BaseSparseNDArray):
                from ..ndarray.sparse import add as _sp_add

                for g in grads[1:]:
                    g0 = _sp_add(g0, g)
            else:
                from ..kvstore import _fused_sum

                g0 = NDArray(_fused_sum([g._data for g in grads]),
                             ctx=g0.ctx, _committed=True)
        sparse = isinstance(g0, BaseSparseNDArray)
        dim = None if sparse else self._dim_for(index, w0)
        if sparse and self.shard_dims.get(index) is not None:
            # dense steps sharded this param's state, then a sparse
            # grad arrived: sparse updates need the FULL state object,
            # so gather the shards and run this param replicated from
            # here on (lazy row updates touch arbitrary rows — a
            # rank-sliced state cannot serve them)
            if index in self.states:
                self.states[index] = self._gather_index(index)
            self.shard_dims[index] = None
        elif sparse and index not in self.states:
            self.shard_dims[index] = None
        self._ensure_state(index, w0)
        if dim is None or self.shard_dims.get(index) is None:
            # unsharded: ONE full state (not one per replica), one
            # update, plain broadcast of the fresh weight
            opt.update_multi_precision(index, w0, g0, self.states[index])
            self._broadcast(w0, weights[1:])
            return
        n = self.n
        shape = w0.shape
        state_shards = self.states[index]
        new_slices = []
        count_before = opt._index_update_count.get(index)
        for r in range(n):
            idx = self.plan.shard_slice(shape, dim, r)
            w_sl = NDArray(w0._data[idx], ctx=w0.ctx, _committed=True)
            g_sl = NDArray(g0._data[idx], ctx=g0.ctx, _committed=True)
            if r > 0:
                # every rank applies the SAME logical step: rewind the
                # counter bump rank r-1's update made so bias
                # correction / schedules see one advance per wall step
                opt._index_update_count[index] = \
                    (count_before
                     if count_before is not None
                     else opt.begin_num_update)
            opt.update_multi_precision(index, w_sl, g_sl,
                                       state_shards[r])
            new_slices.append(w_sl._data)
        # allgather: chunks -> full param, broadcast into every replica
        full = jnp.concatenate(new_slices, axis=dim)
        w0._set_jax(full)
        self._broadcast(w0, weights[1:])
        nbytes = int(np.prod(shape)) * w0.dtype.itemsize
        ring = (n - 1) / float(n)
        _prof.inc_stat("allgather_bytes", int(nbytes * ring))
        _prof.inc_stat("reduce_scatter_bytes",
                       int(g0.dtype.itemsize * int(np.prod(g0.shape))
                           * ring))

    @staticmethod
    def _broadcast(src: NDArray, dsts: List[NDArray]) -> None:
        for d in dsts:
            if d is src:
                continue
            src.copyto(d)

    # -- Updater duck type ------------------------------------------------
    def __call__(self, index, grad, weight):
        """Single-replica fallback (kvstore updater signature)."""
        self.update_replicas([(index, [grad], [weight])])

    def update_multi(self, triples):
        """`Updater.update_multi` shape: [(index, grad, weight), ...]
        on ONE replica — wrap into the replica form."""
        self.update_replicas([(i, [g], [w]) for i, g, w in triples])

    # -- checkpointing ----------------------------------------------------
    def _gather_index(self, index):
        """One param's state shards -> the full state object."""
        import jax.numpy as jnp

        st = self.states[index]
        dim = self.shard_dims.get(index)
        if dim is None:
            return st
        return _zip_states(
            st, lambda nds, d=dim: NDArray(
                jnp.concatenate([x._data for x in nds], axis=d),
                ctx=nds[0].ctx, _committed=True))

    def _gather_full(self) -> Dict[Any, Any]:
        """Shards -> full host states (replica-count independent)."""
        return {index: self._gather_index(index)
                for index in self.states}

    def get_states(self, dump_optimizer: bool = True) -> bytes:
        """Same wire format as `optimizer.Updater.get_states`; shards
        are gathered first so the payload loads anywhere.  Unlike the
        plain updater, the update counters ride along BY DEFAULT: a
        sharded checkpoint resumes with the exact Adam timestep on any
        replica count (the round-trip regression in
        tests/test_sharding.py)."""
        opt_state = None
        if dump_optimizer:
            opt_state = {
                "num_update": self.optimizer.num_update,
                "begin_num_update": self.optimizer.begin_num_update,
                "_index_update_count": dict(
                    self.optimizer._index_update_count),
            }
        from .. import profiler as _prof

        _prof.inc_stat("zero1_state_gathers", 1)
        return pickle.dumps((self._gather_full(), opt_state))

    def set_states(self, states) -> None:
        """Load full (or plain-Updater) states, RE-SHARDING under the
        active plan — works across a changed replica count."""
        st = pickle.loads(states) if isinstance(states, bytes) else states
        opt_state = None
        if isinstance(st, tuple) and len(st) == 2:
            st, opt_state = st
        if opt_state is not None:
            self.optimizer.__dict__.update(opt_state)
        self.states = {}
        self.shard_dims = {}
        self.states_synced = {}
        for index, full in st.items():
            leaf = _first_leaf(full)
            if leaf is None:
                self.states[index] = full
                self.shard_dims[index] = None
                self.states_synced[index] = True
                continue
            dim = self.plan.shard_dim(self._name_of(index), leaf.shape)
            self.shard_dims[index] = dim
            if dim is None:
                self.states[index] = full
            else:
                self.states[index] = [
                    _map_state(full, lambda nd, r=r: NDArray(
                        nd._data[self.plan.shard_slice(nd.shape, dim,
                                                       r)],
                        ctx=nd.ctx, _committed=True))
                    for r in range(self.n)]
            self.states_synced[index] = True
        from .. import profiler as _prof

        _prof.inc_stat("zero1_state_reshards", 1)


def _first_leaf(obj) -> Optional[NDArray]:
    if isinstance(obj, NDArray):
        return obj
    if isinstance(obj, (tuple, list)):
        for o in obj:
            leaf = _first_leaf(o)
            if leaf is not None:
                return leaf
    return None


_MISSING = object()
