"""reshard — cheap layout moves between two ShardingPlans.

The train<->serve primitive (arXiv 2112.01075, memory-efficient array
redistribution): a training run leaves params replicated and optimizer
state ZeRO-sharded over the data axis; serving wants a different mesh
(or a single host) with its own placement.  :func:`reshard` moves a
pytree of jax arrays / NDArrays from the layout one plan prescribes to
another's in ONE device_put per leaf — XLA/PJRT plans the minimal
shard-to-shard copies (no gather-to-host round trip), which is the
memory-efficient path the paper formalizes.

Provenance: every reshard books a signature on the ``reshard:<label>``
`mx.inspect` program record (so `mx.inspect.programs()` shows which
layout moves ran, how often, and blames churn) and emits a telemetry
``reshard`` event carrying both plan descriptions and the payload
bytes; ``reshard_bytes`` accumulates in ``profiler.stats()``.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .plan import ShardingPlan

__all__ = ["reshard"]

# per-label seen-signature sets for inspect retrace accounting
_SEEN: Dict[str, set] = {}


def _leaf_nbytes(x) -> int:
    if hasattr(x, "nbytes"):
        return int(x.nbytes)
    return int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize


def reshard(tree: Any, plan_b: ShardingPlan,
            plan_a: Optional[ShardingPlan] = None,
            kind: str = "params", label: str = "default") -> Any:
    """Move ``tree`` (dict name -> array, or a bare array/NDArray) to
    the layout ``plan_b`` prescribes.

    ``kind`` picks the spec family: ``"params"`` uses
    :meth:`ShardingPlan.spec_for`, ``"opt_state"`` uses
    :meth:`ShardingPlan.opt_state_spec` (the ZeRO-1 placement).  With
    no mesh on ``plan_b`` the leaves are gathered to single-device
    host-committed arrays (the serve-on-one-host move).

    Returns a new tree of the same shape; inputs are not mutated.
    """
    import jax

    from .. import inspect as _insp
    from .. import profiler as _prof
    from .. import telemetry as _tel

    if kind not in ("params", "opt_state"):
        raise MXNetError("reshard kind must be 'params' or 'opt_state'")
    single = not isinstance(tree, dict)
    items = {"_": tree} if single else dict(tree)

    def _target(name, shape):
        if plan_b.mesh is None:
            return None  # single-device gather
        spec = (plan_b.spec_for(name, shape) if kind == "params"
                else plan_b.opt_state_spec(name, shape))
        return plan_b.named_sharding(spec)

    moved: Dict[str, Any] = {}
    total = 0
    for name, val in items.items():
        nd_ctx = val.ctx if isinstance(val, NDArray) else None
        raw = val._data if isinstance(val, NDArray) else val
        sharding = _target(name, raw.shape)
        if sharding is None:
            out = jax.device_put(np.asarray(jax.device_get(raw)))
        else:
            out = jax.device_put(raw, sharding)
        total += _leaf_nbytes(raw)
        moved[name] = NDArray(out, ctx=nd_ctx, _committed=True) \
            if nd_ctx is not None else out
    _prof.inc_stat("reshard_bytes", total)

    desc_a = plan_a.describe() if plan_a is not None else "?"
    desc_b = plan_b.describe()
    rec = _insp.program("reshard", label)
    sig = ("reshard", desc_a, desc_b, kind,
           tuple(sorted((n, tuple(getattr(v, "shape", ())))
                        for n, v in items.items())))
    seen = _SEEN.setdefault("%s:%s" % (label, kind), set())
    tok = _insp.track_compile(rec, seen, "reshard", "reshard", kind, sig)
    if tok is not None:
        tok.done(None, None)
    _tel.record("reshard", site="reshard", label=label, family=kind,
                plan_from=desc_a, plan_to=desc_b, bytes=total,
                n_arrays=len(items))
    return moved["_"] if single else moved
