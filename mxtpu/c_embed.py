"""Python engine behind the flat C ABI (`src/c_api.cc`).

The reference exposes ~200 `MX*` C functions
(`include/mxnet/c_api.h:412` onward) that underpin every language
binding and embedding; its predict ABI got a TPU-native analog in round
4 (`src/predict.cc` over `mxtpu.predict_embed`).  This module is the
engine for the CORE tier of that flat API: NDArray create/copy/save,
op enumeration + imperative invoke, KVStore init/push/pull, and data
iterators — the function groups `python/mxnet/{ndarray,kvstore,io}`
sit on in the reference.

Contract with the C layer: every function takes/returns plain Python
objects; the C side holds `PyObject*`s as opaque handles and frees them
with Py_DECREF.  Keep the module import-light — the embedded
interpreter imports it once per process; mxtpu itself loads lazily on
first use.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = [
    "version", "seed", "wait_all", "list_op_names", "get_op",
    "imperative_invoke", "ndarray_create", "nd_itemsize", "nd_copy_from_bytes",
    "nd_to_bytes", "nd_shape", "nd_dtype_code", "nd_context",
    "nd_save", "nd_load", "kv_create", "kv_init", "kv_push", "kv_pull",
    "iter_create", "iter_before_first", "iter_next", "iter_data",
    "iter_label", "autograd_set_recording", "autograd_set_training",
    "autograd_is_recording", "autograd_is_training",
    "autograd_mark_variables", "autograd_backward", "nd_get_grad",
]


def _mx():
    import mxtpu

    return mxtpu


def version() -> int:
    """MXGetVersion: MAJOR*10000 + MINOR*100 + PATCH (reference
    include/mxnet/base.h MXNET_VERSION encoding)."""
    parts = _mx().__version__.split(".")[:3]
    nums = [int("".join(ch for ch in p if ch.isdigit()) or 0)
            for p in parts]
    while len(nums) < 3:
        nums.append(0)
    return nums[0] * 10000 + nums[1] * 100 + nums[2]


def seed(s: int) -> None:
    _mx().random.seed(int(s))


def wait_all() -> None:
    """MXNDArrayWaitAll: barrier on all outstanding async work — the
    native engine's queues plus device computations."""
    from mxtpu.engine import get_engine

    get_engine().wait_for_all()
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass


def list_op_names() -> List[str]:
    """MXListAllOpNames (c_api.h): every registered operator name."""
    from mxtpu.ops.registry import list_ops

    return sorted(list_ops())


def get_op(name: str) -> str:
    """Analog of NNGetOpHandle / MXSymbolListAtomicSymbolCreators +
    GetAtomicSymbolName: resolve an op name to an opaque handle."""
    from mxtpu.ops.registry import has_op

    if not has_op(name):
        raise KeyError("no such operator: %s" % name)
    return name  # the name itself is a perfectly good opaque handle


def _parse_c_attr(v: str):
    """The C wire format is string-typed attrs (reference
    MXImperativeInvoke keys/vals); parse numbers/tuples/bools the way
    the reference's parameter structs do, leaving enum-ish strings
    (e.g. act_type='relu') alone."""
    import ast

    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        if v in ("True", "true"):
            return True
        if v in ("False", "false"):
            return False
        return v


def imperative_invoke(op_name: str, inputs: Sequence, keys: Sequence[str],
                      vals: Sequence[str]) -> list:
    """MXImperativeInvoke (c_api.h:968): run one op eagerly on NDArray
    handles with string-typed attrs; returns the output NDArray list."""
    from mxtpu.ndarray.ndarray import imperative_invoke as _invoke

    attrs = {k: _parse_c_attr(v) for k, v in zip(keys, vals)}
    out = _invoke(op_name, *list(inputs), **attrs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


# -- NDArray ---------------------------------------------------------------

def _ctx(dev_type: int, dev_id: int):
    mx = _mx()
    # reference dev_type codes: 1=cpu, 2=gpu (tpu here), 3=cpu_pinned
    if dev_type == 2:
        return mx.tpu(dev_id)
    if dev_type == 3:
        return mx.cpu_pinned(dev_id)
    return mx.cpu(dev_id)


def ndarray_create(shape: Sequence[int], dev_type: int, dev_id: int,
                   dtype_code: int):
    """MXNDArrayCreateEx: a zero-initialized array (delay_alloc is
    meaningless under XLA's buffer model)."""
    from mxtpu.base import dtype_mx_to_np

    mx = _mx()
    return mx.nd.zeros(tuple(int(s) for s in shape),
                       ctx=_ctx(dev_type, dev_id),
                       dtype=dtype_mx_to_np(dtype_code))


def nd_itemsize(arr) -> int:
    return int(np.dtype(arr.dtype).itemsize)


def nd_copy_meta(arr, size: int) -> int:
    """Pre-copy validation for MXNDArraySyncCopyFromCPU: checks the
    element count BEFORE the C side reads the caller's buffer (an
    oversized `size` must fail cleanly, not OOB-read), then returns the
    itemsize for the byte-length computation."""
    _check_size(arr, size, "MXNDArraySyncCopyFromCPU")
    return nd_itemsize(arr)


def _check_size(arr, size: int, fn: str) -> None:
    # reference NDArray::SyncCopyFromCPU: CHECK_EQ(shape().Size(), size)
    if int(arr.size) != int(size):
        raise ValueError("%s: size mismatch — array has %d elements, "
                         "caller passed %d" % (fn, int(arr.size), size))


def nd_copy_from_bytes(arr, data: bytes, size: int) -> None:
    """MXNDArraySyncCopyFromCPU: `size` is the element count (reference
    semantics); `data` carries size*itemsize raw little-endian bytes in
    the array's dtype, row-major."""
    _check_size(arr, size, "MXNDArraySyncCopyFromCPU")
    np_val = np.frombuffer(data, dtype=arr.dtype).reshape(arr.shape)
    arr[:] = np_val


def nd_to_bytes(arr, size: int) -> bytes:
    """MXNDArraySyncCopyToCPU: validates the element count, returns the
    full payload."""
    _check_size(arr, size, "MXNDArraySyncCopyToCPU")
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def nd_shape(arr) -> List[int]:
    return [int(s) for s in arr.shape]


def nd_dtype_code(arr) -> int:
    from mxtpu.base import dtype_np_to_mx

    return dtype_np_to_mx(arr.dtype)


def nd_context(arr):
    ctx = arr.ctx
    dev_type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3,
                "cpu_shared": 5}.get(ctx.device_type, 1)
    return (dev_type, int(ctx.device_id))


def nd_save(fname: str, arrays: Sequence, keys: Sequence[str]) -> None:
    """MXNDArraySave: same container format as mx.nd.save (round-trips
    with the Python frontend)."""
    mx = _mx()
    if keys:
        mx.nd.save(fname, dict(zip(keys, arrays)))
    else:
        mx.nd.save(fname, list(arrays))


def nd_load(fname: str):
    """MXNDArrayLoad -> (arrays, names); names is empty for list
    containers."""
    mx = _mx()
    loaded = mx.nd.load(fname)
    if isinstance(loaded, dict):
        names = list(loaded.keys())
        return [loaded[k] for k in names], names
    return list(loaded), []


# -- KVStore ---------------------------------------------------------------

def kv_create(kv_type: str):
    return _mx().kv.create(kv_type)


def _kv_keys(keys):
    return [int(k) for k in keys]


def kv_init(kv, keys, vals) -> None:
    kv.init(_kv_keys(keys), list(vals))


def kv_push(kv, keys, vals, priority: int) -> None:
    kv.push(_kv_keys(keys), list(vals), priority=priority)


def kv_pull(kv, keys, outs, priority: int) -> None:
    kv.pull(_kv_keys(keys), out=list(outs), priority=priority)


# -- Data iterators --------------------------------------------------------

_ITER_ARG_TYPES = {
    "batch_size": int, "shuffle": lambda v: v not in ("0", "False",
                                                      "false", ""),
    "last_batch_handle": str, "data_name": str, "label_name": str,
    "round_batch": lambda v: v not in ("0", "False", "false", ""),
    "num_parts": int, "part_index": int, "prefetch_depth": int,
}


class _CIter(object):
    """Holds the iterator plus the current batch for GetData/GetLabel
    (reference MXDataIterGetData semantics: valid until the next
    Next())."""

    def __init__(self, it):
        self.it = it
        self.batch = None


def iter_create(name: str, keys: Sequence[str], vals: Sequence[str]):
    """MXListDataIters + MXDataIterCreateIter: create a registered
    iterator by name with string-typed kwargs (the C wire format).
    Array-valued kwargs (data/label) are file paths or unsupported from
    C — NDArrayIter from C feeds via `data_handle`-style kwargs is out
    of scope; CSVIter/MNISTIter/LibSVMIter cover the C use case."""
    import mxtpu.io as mio

    kwargs: Dict[str, object] = {}
    for k, v in zip(keys, vals):
        conv = _ITER_ARG_TYPES.get(k)
        if conv is not None:
            kwargs[k] = conv(v)
        else:
            # shapes arrive as "(a, b)" tuples, everything else raw
            vs = v.strip()
            if vs.startswith("("):
                kwargs[k] = tuple(
                    int(t) for t in vs.strip("()").split(",") if t.strip())
            else:
                kwargs[k] = v
    return _CIter(mio.create(name, **kwargs))


def iter_before_first(ci: _CIter) -> None:
    ci.it.reset()
    ci.batch = None


def iter_next(ci: _CIter) -> bool:
    try:
        ci.batch = ci.it.next()
        return True
    except StopIteration:
        ci.batch = None
        return False


def iter_data(ci: _CIter):
    return ci.batch.data[0]


def iter_label(ci: _CIter):
    return ci.batch.label[0]


# -- Autograd (reference c_api.h:1004-1050) --------------------------------

def autograd_set_recording(flag: int) -> int:
    from mxtpu import autograd

    return int(autograd.set_recording(bool(flag)))


def autograd_set_training(flag: int) -> int:
    from mxtpu import autograd

    return int(autograd.set_training(bool(flag)))


def autograd_is_recording() -> int:
    from mxtpu import autograd

    return int(autograd.is_recording())


def autograd_is_training() -> int:
    from mxtpu import autograd

    return int(autograd.is_training())


def autograd_mark_variables(arrs, grad_reqs, grads) -> None:
    """MXAutogradMarkVariables: attach gradient buffers.  grad_req
    codes follow the reference's _GRAD_REQ_MAP (ndarray.py:94):
    0=null, 1=write, 3=add (2 is kWriteInplace, not exposed there
    either); unknown codes error instead of silently writing."""
    from mxtpu import autograd

    req_names = {0: "null", 1: "write", 3: "add"}
    reqs = []
    for r in grad_reqs:
        if int(r) not in req_names:
            raise ValueError("MXAutogradMarkVariables: unsupported "
                             "grad_req code %d (0=null, 1=write, "
                             "3=add)" % int(r))
        reqs.append(req_names[int(r)])
    autograd.mark_variables(list(arrs), list(grads), reqs)


def autograd_backward(outputs, out_grads, retain_graph: int,
                      train_mode: int) -> None:
    """MXAutogradBackward."""
    from mxtpu import autograd

    autograd.backward(list(outputs),
                      list(out_grads) if out_grads else None,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))


def nd_get_grad(arr):
    """MXNDArrayGetGrad: the grad buffer attached by mark_variables."""
    g = arr.grad
    if g is None:
        raise ValueError("array has no gradient buffer "
                         "(MXAutogradMarkVariables first)")
    return g


# -- Symbol + Executor (reference c_api_symbolic.cc / c_api_executor.cc) ----

def symbol_from_json(json_str: str):
    """MXSymbolCreateFromJSON."""
    from mxtpu.symbol.symbol import load_json

    return load_json(json_str)


def symbol_to_json(sym) -> str:
    """MXSymbolSaveToJSON."""
    return sym.tojson()


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def _decode_csr_shapes(keys, indptr, shape_data):
    """Reference CSR shape wire format -> {name: shape} (shared by
    symbol_infer_shape and executor_simple_bind)."""
    return {k: tuple(int(s)
                     for s in shape_data[indptr[i]:indptr[i + 1]])
            for i, k in enumerate(keys)}


def symbol_infer_shape(sym, keys, indptr, shape_data):
    """MXSymbolInferShape (reference CSR wire format in; three
    (arg, out, aux) shape lists out)."""
    arg_s, out_s, aux_s = sym.infer_shape(
        **_decode_csr_shapes(keys, indptr, shape_data))
    as_lists = lambda seq: [[int(d) for d in (s or ())] for s in seq]
    return as_lists(arg_s), as_lists(out_s), as_lists(aux_s)


def executor_simple_bind(sym, dev_type: int, dev_id: int, keys, indptr,
                         shape_data, grad_req_code: int):
    """MXExecutorSimpleBind: grad_req_code 0=null, 1=write — applied to
    EVERY argument.  Passed as an explicit per-arg dict because the
    python-level simple_bind treats string grad_req + provided shape as
    "data input, null grad" — a C caller naturally provides all shapes
    and still expects gradients."""
    shapes = _decode_csr_shapes(keys, indptr, shape_data)
    req = {0: "null", 1: "write"}.get(int(grad_req_code))
    if req is None:
        raise ValueError("grad_req code %d (0=null, 1=write)"
                         % grad_req_code)
    req_dict = {name: req for name in sym.list_arguments()}
    return sym.simple_bind(ctx=_ctx(dev_type, dev_id),
                           grad_req=req_dict, **shapes)


def executor_set_arg(exe, name: str, arr) -> None:
    """Copy `arr` into the named argument (data, label, or parameter)
    — the C-side analog of writing exe.arg_dict[name][:]."""
    if name in exe.arg_dict:
        arr.copyto(exe.arg_dict[name])
    elif name in exe.aux_dict:
        arr.copyto(exe.aux_dict[name])
    else:
        raise KeyError("executor has no argument %r" % name)


def executor_forward(exe, is_train: int) -> None:
    exe.forward(is_train=bool(is_train))


def executor_outputs(exe):
    return list(exe.outputs)


def executor_backward(exe, ograds) -> None:
    """MXExecutorBackward; empty ograds = scalar-loss heads."""
    exe.backward(list(ograds) if ograds else None)


def executor_arg_grad(exe, name: str):
    """Gradient buffer of a bound argument after backward."""
    grads = dict(zip(symbol_list_arguments(exe._symbol),
                     exe.grad_arrays))
    g = grads.get(name)
    if g is None:
        raise KeyError("no gradient for argument %r (grad_req null?)"
                       % name)
    return g


# -- CachedOp (reference c_api_ndarray.cc MXCreateCachedOp[Ex]) ------------

def cached_op_create(sym):
    """MXCreateCachedOp: compile the symbol once; invocations reuse the
    jitted module."""
    from mxtpu.cached_op import CachedOp

    return CachedOp(sym)


def cached_op_invoke(co, inputs):
    """MXInvokeCachedOp: inputs are the arguments in
    symbol.list_arguments() order FOLLOWED by the auxiliary states in
    symbol.list_auxiliary_states() order (reference semantics: aux
    travels among the inputs; aux handles are updated in place)."""
    inputs = list(inputs)
    n_args = len(co._arg_names)
    out = co(inputs[:n_args], inputs[n_args:])
    return list(out) if isinstance(out, (list, tuple)) else [out]


# -- KVStore cluster queries (reference MXKVStoreGetRank/GroupSize) --------

def kv_rank(kv) -> int:
    return int(kv.rank)


def kv_num_workers(kv) -> int:
    return int(kv.num_workers)


def kv_barrier(kv) -> None:
    kv.barrier()


# -- Profiler (reference MXSetProfilerConfig/State, MXDumpProfile,
#    MXAggregateProfileStatsPrint) --------------------------------------

def profiler_set_config(keys, vals) -> None:
    """Boolean/numeric flags parse from the string wire format;
    path-valued keys stay raw strings (a numeric 'filename' must not
    become an int fd)."""
    from mxtpu import profiler

    # only "filename" is both consumed AND type-sensitive (a numeric
    # path must stay a string, not become an os fd)
    profiler.set_config(**{k: (v if k == "filename"
                               else _parse_c_attr(v))
                           for k, v in zip(keys, vals)})


def profiler_set_state(state: int) -> None:
    from mxtpu import profiler

    profiler.set_state("run" if state else "stop")


def profiler_dump(finished: int) -> None:
    from mxtpu import profiler

    profiler.dump(bool(finished))


def profiler_aggregate_stats(reset: int) -> str:
    from mxtpu import profiler

    return profiler.dumps(reset=bool(reset))


# -- NDArray views / serialization widening (r5s3; reference
#    c_api.h: MXNDArrayReshape/Slice/At/Detach/GetStorageType/
#    SaveRawBytes/LoadFromRawBytes/LoadFromBuffer/SyncCopyFromNDArray/
#    WaitToRead/WaitToWrite/CreateNone/Get+SetGradState) ------------------

_STYPE_CODES = {"undefined": -1, "default": 0, "row_sparse": 1, "csr": 2}


def nd_create_none():
    """MXNDArrayCreateNone: a placeholder handle (reference returns an
    empty NDArray; here a zero-size f32 vector on cpu)."""
    mx = _mx()
    return mx.nd.zeros((0,))


def nd_reshape(arr, shape):
    """MXNDArrayReshape/Reshape64 (supports -1 wildcard like the
    reference's TShape inference)."""
    return arr.reshape(tuple(int(s) for s in shape))


def nd_slice(arr, begin: int, end: int):
    """MXNDArraySlice: axis-0 contiguous range.  XLA arrays are
    immutable values, so unlike the reference this is a copy, not an
    aliasing view — documented divergence (docs/c_api.md)."""
    return arr[int(begin):int(end)]


def nd_at(arr, idx: int):
    """MXNDArrayAt: axis-0 single index (rank reduces by one)."""
    return arr[int(idx)]


def nd_detach(arr):
    return arr.detach()


def nd_storage_type(arr) -> int:
    return _STYPE_CODES.get(getattr(arr, "stype", "default"), -1)


def nd_wait_to_read(arr) -> None:
    arr.wait_to_read()


def nd_wait_to_write(arr) -> None:
    # PJRT buffers are immutable; every write makes a new buffer, so
    # write-readiness == read-readiness of the current value
    arr.wait_to_read()


_FRESH_GRAD: dict = {}  # id(arr) -> bool; entries die with the array


def nd_grad_state(arr) -> int:
    """MXNDArrayGetGradState: the reference's fresh_out_grad bit —
    frontend bookkeeping for 'grad was just written by backward',
    NOT the requires-grad/taping flag (touching `_marked` here would
    silently enable/disable autograd tracking).  Kept in an
    identity-keyed side table like the reference keeps `_fresh_grad`
    on the Python object (a WeakKeyDictionary would compare keys with
    NDArray's elementwise `__eq__`)."""
    return 1 if _FRESH_GRAD.get(id(arr)) else 0


def nd_set_grad_state(arr, state: int) -> None:
    import weakref

    key = id(arr)
    if key not in _FRESH_GRAD:
        weakref.finalize(arr, _FRESH_GRAD.pop, key, None)
    _FRESH_GRAD[key] = bool(state)


def nd_save_raw_bytes(arr) -> bytes:
    """MXNDArraySaveRawBytes: self-describing single-array payload
    (the same container nd.save uses, so it round-trips with
    LoadFromRawBytes across processes)."""
    import io

    mx = _mx()
    buf = io.BytesIO()
    mx.nd.save(buf, [arr])
    return buf.getvalue()


def nd_load_from_raw_bytes(data: bytes):
    """MXNDArrayLoadFromRawBytes: inverse of nd_save_raw_bytes."""
    arrays, _ = _load_from_bytes(data)
    if len(arrays) != 1:
        raise ValueError("raw-bytes payload holds %d arrays, expected 1"
                         % len(arrays))
    return arrays[0]


def _load_from_bytes(data: bytes):
    import io

    mx = _mx()
    loaded = mx.nd.load(io.BytesIO(bytes(data)))
    if isinstance(loaded, dict):
        names = list(loaded.keys())
        return [loaded[k] for k in names], names
    return list(loaded), []


def nd_load_from_buffer(data: bytes):
    """MXNDArrayLoadFromBuffer -> (arrays, names): in-memory analog of
    MXNDArrayLoad (same container format)."""
    return _load_from_bytes(data)


def nd_sync_copy_from_ndarray(dst, src) -> None:
    """MXNDArraySyncCopyFromNDArray: dst[:] = src (shape/dtype adapt
    follows the reference's CopyFromTo semantics: shapes must match)."""
    if tuple(dst.shape) != tuple(src.shape):
        raise ValueError(
            "MXNDArraySyncCopyFromNDArray: shape mismatch %r vs %r"
            % (tuple(src.shape), tuple(dst.shape)))
    src.copyto(dst)
    dst.wait_to_read()


# -- RecordIO (reference MXRecordIOReader*/Writer*; backed by the same
#    wire-compatible mxtpu.recordio used from Python) ----------------------

def recordio_writer_create(path: str):
    from mxtpu import recordio

    return recordio.MXRecordIO(path, "w")


def recordio_reader_create(path: str):
    from mxtpu import recordio

    return recordio.MXRecordIO(path, "r")


def recordio_write(rec, data: bytes) -> None:
    rec.write(bytes(data))


def recordio_read(rec):
    """Returns the next record's bytes, or None at EOF (the C shim maps
    None to size=0, the reference's EOF convention)."""
    return rec.read()


def recordio_tell(rec) -> int:
    return int(rec.tell())


def recordio_seek(rec, pos: int) -> None:
    rec.seek(int(pos))


def recordio_close(rec) -> None:
    rec.close()


# -- KVStore role/config queries (reference MXKVStoreGetType/
#    GetNumDeadNode/IsWorkerNode/IsServerNode/IsSchedulerNode/
#    SetGradientCompression) ----------------------------------------------

def kv_type(kv) -> str:
    return str(kv.type)


def kv_num_dead_node(kv, node_id: int) -> int:
    if hasattr(kv, "get_num_dead_node"):
        return int(kv.get_num_dead_node(node_id))
    return 0


def kv_role() -> str:
    """Node role from the PS env (reference: role env var drives
    MXKVStoreIs{Worker,Server,Scheduler}Node)."""
    import os

    return os.environ.get("DMLC_ROLE", "worker")


def kv_set_gradient_compression(kv, keys, vals) -> None:
    kv.set_gradient_compression(dict(zip(keys, vals)))


# -- misc (reference MXGetGPUCount/MXEngineSetBulkSize) --------------------

def accelerator_count() -> int:
    """MXGetGPUCount analog: number of accelerator devices (TPU here)."""
    mx = _mx()
    return int(mx.num_tpus())


def engine_set_bulk_size(size: int) -> int:
    """MXEngineSetBulkSize: XLA fuses whole programs, so bulking is a
    no-op here; accept and echo the previous value for ABI parity."""
    global _BULK_SIZE
    prev = globals().get("_BULK_SIZE", 0)
    _BULK_SIZE = int(size)
    return int(prev)


# -- DataIter extras / autograd ex (r5s3 widening, second batch) -----------

def list_data_iters():
    """MXListDataIters: registered iterator names."""
    from mxtpu.io.io import _ITER_REGISTRY

    return sorted(_ITER_REGISTRY)


def iter_pad_num(ci) -> int:
    """MXDataIterGetPadNum: pad count of the CURRENT batch (0 when the
    iterator fills batches exactly)."""
    b = ci.batch
    return int(getattr(b, "pad", 0) or 0) if b is not None else 0


def iter_get_index(ci):
    """MXDataIterGetIndex -> list of uint64 sample indices (empty when
    the iterator does not track order)."""
    b = ci.batch
    idx = getattr(b, "index", None) if b is not None else None
    if idx is None:
        return []
    return [int(i) for i in np.asarray(idx).ravel()]


def autograd_backward_ex(outputs, out_grads, variables, retain_graph: int,
                         create_graph: int, is_train: int):
    """MXAutogradBackwardEx: with variables, computes and RETURNS the
    per-variable gradients (the reference's grad() path, leaving .grad
    buffers untouched); without, behaves like MXAutogradBackward."""
    from mxtpu import autograd

    if variables:
        grads = autograd.grad(list(outputs), list(variables),
                              head_grads=(list(out_grads)
                                          if out_grads else None),
                              retain_graph=bool(retain_graph),
                              create_graph=bool(create_graph),
                              train_mode=bool(is_train))
        return list(grads)
    if create_graph:
        # backward() accumulates into .grad buffers, which are not
        # taped — silently returning first-order grads would corrupt a
        # higher-order caller; the taped path requires variables
        raise ValueError("MXAutogradBackwardEx: create_graph=1 "
                         "requires num_variables>0 (the grad() path); "
                         ".grad accumulation is not taped")
    autograd.backward(list(outputs),
                      list(out_grads) if out_grads else None,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(is_train))
    return []


def kv_set_updater(kv, fn) -> None:
    """MXKVStoreSetUpdater: fn(key, recv_merged, stored) — the C
    trampoline forwards to the caller's function pointer; ownership of
    the two handles passes to the C callback (it frees them with
    MXNDArrayFree, reference updater protocol).  fn=None clears the
    updater (C side maps a NULL function pointer here)."""
    kv.set_updater(fn)


# -- PS env / server hosting (reference MXInitPSEnv, MXKVStoreRunServer,
#    MXKVStoreSendCommmandToServers [sic - reference header spelling]) -----

def kv_init_ps_env(keys, vals) -> None:
    """MXInitPSEnv: install the DMLC_* cluster env vars (role, scheduler
    address, counts) before kv_create of a dist store."""
    import os

    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)


def kv_send_command(kv, head: int, body: bytes) -> None:
    """MXKVStoreSendCommmandToServers: the reference wire format is an
    int command id + opaque body; our PS command channel is
    string-headed, so the id travels as str(head)."""
    kv.send_command_to_servers(str(int(head)), bytes(body))


def kv_run_server(kv, controller=None) -> None:
    """MXKVStoreRunServer: blocks serving when DMLC_ROLE is server or
    scheduler (raises for worker, matching KVStoreServer).  controller
    receives (head, body) for non-builtin commands; the C trampoline
    maps head back to the int id."""
    from mxtpu.kvstore_server import KVStoreServer

    if controller is None:
        KVStoreServer().run()
        return

    def _ctl(head, body):
        try:
            h = int(head)
        except (TypeError, ValueError):
            h = -1
        controller(h, body if isinstance(body, (bytes, bytearray))
                   else str(body).encode())

    KVStoreServer().run(controller=_ctl)
