"""Model helpers: kvstore wiring + checkpointing + legacy FeedForward.

Reference: `python/mxnet/model.py` — `_create_kvstore` (:125),
`_update_params_on_kvstore` (:145), `save_checkpoint/load_checkpoint`
(:383,413), `BatchEndParam`, and the legacy `FeedForward` API.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Dict, List, Optional, Tuple

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray import ndarray as nd_mod
from .ndarray.ndarray import NDArray
from . import kvstore as kvs
from . import resilience as _res
from . import symbol as sym_mod

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_latest", "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device: int, arg_params):
    """Decide kvstore + update_on_kvstore (reference `model.py:58-99`)."""
    update_on_kvstore = True
    if kvstore is None or kvstore == "":
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and kvstore != "tpu":
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # reference heuristic (`model.py:58-99`): models with a
                # big (>16M-element) param update per-device, not on the
                # single merge device
                max_size = max(int(np.prod(p.shape)) for p in
                               arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise MXNetError("bad kvstore %r" % (kvstore,))
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    """Push grads / pull weights (reference `model.py:145`); priority
    -index so earlier-needed keys schedule first."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Aggregate via kvstore (push+pull grads) then run the updater per
    device (reference `model.py:165-201`).

    With a :class:`mxtpu.sharding.ZeRO1Updater` (Module engaged a
    `ShardingPlan`) the per-device update loop is replaced by ONE
    cross-replica sharded update: the updater slices the merged grad,
    applies the optimizer on each replica's 1/N state chunk, and
    allgathers the params back into every replica — no per-device
    state redundancy (`docs/sharding.md`)."""
    from .sharding.zero1 import ZeRO1Updater

    if isinstance(updater, ZeRO1Updater):
        triples = []
        for i, (arg_list, grad_list) in enumerate(zip(param_arrays,
                                                      grad_arrays)):
            if grad_list[0] is None:
                continue
            if kvstore:
                name = param_names[i]
                kvstore.push(name, grad_list, priority=-i)
                kvstore.pull(name, grad_list, priority=-i)
            triples.append((i, grad_list, arg_list))
        updater.update_replicas(triples, pre_reduced=kvstore is not None)
        return
    updates: List[List[Tuple]] = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, (w, g) in enumerate(zip(arg_list, grad_list)):
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        if hasattr(updater, "update_multi"):
            updater.update_multi(dev_updates)  # one fused XLA call
        else:
            for idx, g, w in dev_updates:
                updater(idx, g, w)


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params,
                    aux_params, remove_amp_cast=True, states=None,
                    extra_meta=None):
    """Write `prefix-symbol.json` + `prefix-%04d.params` (reference
    `model.py:383`) — ATOMICALLY: every member lands via
    temp+fsync+rename and a CRC32 manifest
    (`prefix-%04d.manifest.json`) is committed LAST, so a crash (even
    SIGKILL) mid-save can never truncate the previous checkpoint and a
    checkpoint without a valid manifest is recognizably partial
    (`load_latest` skips it).  ``states`` optionally embeds serialized
    optimizer state as `prefix-%04d.states`.  All IO runs under the
    ``checkpoint`` fault-injection site + retry policy
    (mxtpu/resilience.py).  ``extra_meta`` (a JSON-serializable dict)
    rides in the manifest payload — `mx.checkpoint` uses it to stamp
    fleet ids and run state next to the tensors they describe."""
    writer = _res.CheckpointWriter(prefix, epoch)

    def _member(path, write_fn):
        def body():
            with writer.file(path) as f:
                write_fn(f)
        _res.run_with_retry("checkpoint", body)

    if symbol is not None:
        _member("%s-symbol.json" % prefix,
                lambda f: f.write(symbol.tojson().encode()))
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    _member("%s-%04d.params" % (prefix, epoch),
            lambda f: nd_mod.save(f, save_dict))
    if states is not None:
        _member("%s-%04d.states" % (prefix, epoch),
                lambda f: f.write(states))
    writer.commit(extra=extra_meta if extra_meta else None)


def read_checkpoint_meta(prefix: str, epoch: int):
    """The manifest payload of ``prefix``/``epoch`` as a dict (CRCs,
    file list, any ``extra_meta`` saved alongside) — or None when no
    manifest exists.  Cheap: reads only the JSON manifest, never the
    tensor members."""
    return _res.read_manifest(prefix, epoch)


def load_checkpoint(prefix: str, epoch: int):
    """Load (symbol, arg_params, aux_params) (reference `model.py:413`)."""
    def body():
        _res.maybe_fault("checkpoint", prefix)
        symbol = sym_mod.load("%s-symbol.json" % prefix)
        save_dict = nd_mod.load("%s-%04d.params" % (prefix, epoch))
        return symbol, save_dict
    symbol, save_dict = _res.run_with_retry("checkpoint", body)
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


def load_latest(prefix: str):
    """Auto-resume: load the NEWEST complete checkpoint for ``prefix``,
    skipping corrupt/partial ones (CRC-validated manifests, newest
    first).  Falls back to probing bare ``prefix-NNNN.params`` files for
    pre-manifest checkpoints.  Returns ``(symbol, arg_params,
    aux_params, epoch)`` or None when nothing restorable exists."""
    epoch = _res.latest_valid_epoch(prefix)
    if epoch is not None:
        sym, args, auxs = load_checkpoint(prefix, epoch)
        return sym, args, auxs, epoch
    # legacy checkpoints (saved before the manifest format existed)
    import glob

    from . import profiler as _prof

    covered = set(_res.list_manifest_epochs(prefix))
    for path in sorted(
            glob.glob("%s-[0-9][0-9][0-9][0-9].params" % prefix),
            reverse=True):
        ep = int(path[-len("0000.params"):-len(".params")])
        if ep in covered:  # manifest said corrupt; don't resurrect it
            continue
        try:
            sym, args, auxs = load_checkpoint(prefix, ep)
            return sym, args, auxs, ep
        except Exception:
            _prof.inc_stat("checkpoint_skipped_corrupt")
    return None


class FeedForward(object):
    """Legacy estimator-style API (reference `model.py` FeedForward;
    deprecated there in favor of Module — provided as a thin veneer over
    `mxtpu.module.Module` for API parity)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, begin_epoch=0,
                 **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, (list, tuple)) else \
            [ctx or current_context()]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def _make_module(self, data_names, label_names):
        from .module import Module

        return Module(self.symbol, data_names=data_names,
                      label_names=label_names, context=self.ctx)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, optimizer_params=None):
        mod = self._make_module([d[0] for d in X.provide_data],
                                [l[0] for l in X.provide_label])
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=optimizer_params or
                {"learning_rate": 0.01},
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None):
        if self._module is None:
            raise MXNetError("fit() first")
        outs = self._module.predict(X, num_batch=num_batch)
        return outs.asnumpy() if isinstance(outs, NDArray) else \
            [o.asnumpy() for o in outs]

    def score(self, X, eval_metric="acc", num_batch=None):
        if self._module is None:
            raise MXNetError("fit() first")
        res = self._module.score(X, eval_metric, num_batch=num_batch)
        return res[0][1]

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None else
                        (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)
