"""ImageRecordIter family — recordio-backed image pipelines.

Reference: `src/io/iter_image_recordio_2.cc` (ImageRecordIter: chunked
record reads + OMP-parallel JPEG decode `ParseChunk :78-150`),
`src/io/image_aug_default.cc` (crop/resize/mirror/HSL augmenters),
`src/io/iter_batchloader.h`.

TPU-native design: whole-batch decode tasks are scheduled ahead of the
consumer on the dependency engine (`mxtpu.engine` — native C++ worker
threads when `src/` is built), each task fanning record decode across a
host thread pool; recordio payloads stage through the native storage
pool (`src/storage.cc`) so the read path does no malloc per record.
The consumer pops finished batches — one device transfer per batch —
while the next `prefetch_buffer` batches decode behind it, overlapping
IO with the training step exactly as the reference's prefetcher does
(`src/io/iter_prefetcher.h`).  `MXTPU_ENGINE_TYPE=NaiveEngine`
serializes every decode at schedule time for debugging.  Distributed
sharding (num_parts/part_index) mirrors the reference's `InputSplit`
behavior.
"""
from __future__ import annotations

import io as _pyio
import queue
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import array as nd_array
from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "ImageRecordIter_v1", "ImageRecordUInt8Iter",
           "ImageDetRecordIter"]


def _decode_image(buf: bytes, shape_hint=None) -> np.ndarray:
    """Decode an image payload to HWC uint8.  JPEG/PNG via PIL; `.npy`
    payloads (recordio.pack_img fallback) via np.load; raw byte buffers
    are reshaped from the hint or inferred as square HWC."""
    try:
        from PIL import Image
        img = Image.open(_pyio.BytesIO(buf))
        return np.asarray(img.convert("RGB"), dtype=np.uint8)
    except Exception:
        pass
    if buf[:6] == b"\x93NUMPY":
        return np.load(_pyio.BytesIO(buf), allow_pickle=False)
    arr = np.frombuffer(buf, dtype=np.uint8)
    if shape_hint is not None and arr.size == int(np.prod(shape_hint)):
        return arr.reshape(shape_hint)
    for ch in (3, 1):  # square HWC inference for raw test payloads
        side = int(round((arr.size / ch) ** 0.5))
        if side * side * ch == arr.size:
            return arr.reshape(side, side, ch)
    raise MXNetError("cannot decode %d-byte image payload" % len(buf))


def _resize_shorter(img: np.ndarray, size: int) -> np.ndarray:
    """Resize shorter edge to `size` keeping aspect (reference
    `image_aug_default.cc` resize)."""
    h, w = img.shape[:2]
    if min(h, w) == size:
        return img
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return _resize(img, nh, nw)


def _resize(img: np.ndarray, nh: int, nw: int) -> np.ndarray:
    try:
        from PIL import Image
        return np.asarray(
            Image.fromarray(img).resize((nw, nh), Image.BILINEAR),
            dtype=img.dtype)
    except Exception:
        # nearest-neighbor numpy fallback
        h, w = img.shape[:2]
        ri = (np.arange(nh) * h // nh).clip(0, h - 1)
        ci = (np.arange(nw) * w // nw).clip(0, w - 1)
        return img[ri][:, ci]


class ImageRecordIter(DataIter):
    """Threaded recordio image iterator (reference registered iterator
    `ImageRecordIter`, `src/io/iter_image_recordio_2.cc`).

    Supported params mirror the reference's common surface:
    path_imgrec, data_shape (C,H,W), batch_size, shuffle, rand_crop,
    rand_mirror, resize (shorter edge), mean_r/g/b, std_r/g/b,
    preprocess_threads, round_batch, num_parts/part_index,
    label_width.
    """

    _dtype = np.float32
    _label_fill = 0.0  # padded label slots (det variant uses -1 sentinel)

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, resize=-1, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                 preprocess_threads=4, round_batch=True, num_parts=1,
                 part_index=0, label_width=1, seed=0,
                 prefetch_buffer=2, **_):
        super(ImageRecordIter, self).__init__(batch_size)
        self.data_shape = tuple(int(x) for x in data_shape)
        if len(self.data_shape) != 3:
            raise MXNetError("data_shape must be (C,H,W)")
        self.label_width = int(label_width)
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = int(resize)
        self.mean = np.array([mean_r, mean_g, mean_b],
                             dtype=np.float32).reshape(3, 1, 1)
        self.std = np.array([std_r, std_g, std_b],
                            dtype=np.float32).reshape(3, 1, 1)
        self.round_batch = round_batch
        self.nthreads = max(1, int(preprocess_threads))
        self._rng = np.random.RandomState(seed)

        # index all record offsets once (one sequential scan), then the
        # epoch order can shuffle / shard without touching payloads
        self._path = path_imgrec
        self._offsets: List[int] = []
        rec = MXRecordIO(path_imgrec, "r")
        while True:
            pos = rec.tell()
            if rec.read() is None:
                break
            self._offsets.append(pos)
        rec.close()
        if num_parts > 1:  # distributed shard (reference InputSplit)
            self._offsets = self._offsets[part_index::num_parts]
        if not self._offsets:
            raise MXNetError("no records in %s" % path_imgrec)
        self._epoch_order = np.arange(len(self._offsets))
        self._reader = open(path_imgrec, "rb")
        self._lock = threading.Lock()

        # decode-ahead pipeline: batch decode tasks ride the dependency
        # engine, serialized by one var so completion order == schedule
        # order; `prefetch_buffer` batches stay in flight
        from .. import engine as _engine_mod

        self._engine = _engine_mod.get_engine()
        self._var = self._engine.new_var()
        self._prefetch = max(1, int(prefetch_buffer))
        self._done_q: "queue.Queue" = queue.Queue()
        self._inflight = 0
        self.reset()

    # -- record access ------------------------------------------------------
    def _read_at(self, offset):
        """Read one record payload.  Returns (payload, pooled): with the
        native runtime built the payload is a zero-copy memoryview into
        a `src/storage.cc` pool block (same-bucket reads recycle the
        same host memory — no malloc per record) and the caller releases
        `pooled` once decoded; otherwise plain bytes and None."""
        import struct as _struct

        from .. import _native

        with self._lock:
            self._reader.seek(offset)
            header = self._reader.read(8)
            magic, lrec = _struct.unpack("<II", header)
            length = lrec & ((1 << 29) - 1)
            if _native.available():
                buf = _native.PooledBuffer(length)
                view = memoryview(buf.view).cast("B")
                got = self._reader.readinto(view)
                return view[:got], buf
            return self._reader.read(length), None

    # -- augmentation -------------------------------------------------------
    def _augment(self, img: np.ndarray, rng) -> np.ndarray:
        c, th, tw = self.data_shape
        if self.resize > 0:
            img = _resize_shorter(img, self.resize)
        h, w = img.shape[:2]
        if h < th or w < tw:
            img = _resize(img, max(h, th), max(w, tw))
            h, w = img.shape[:2]
        if self.rand_crop:
            y0 = rng.randint(0, h - th + 1)
            x0 = rng.randint(0, w - tw + 1)
        else:
            y0, x0 = (h - th) // 2, (w - tw) // 2
        img = img[y0:y0 + th, x0:x0 + tw]
        if self.rand_mirror and rng.randint(2):
            img = img[:, ::-1]
        chw = img.astype(np.float32).transpose(2, 0, 1)[:c]
        return (chw - self.mean[:c]) / self.std[:c]

    def _decode_one(self, offset, rng) -> Tuple[np.ndarray, np.ndarray]:
        payload, pooled = self._read_at(offset)
        header, img_buf = unpack(payload)
        # copy: header.label may view pooled memory released below
        label = np.array(np.atleast_1d(np.asarray(header.label,
                                                  dtype=np.float32)))
        c, h, w = self.data_shape
        img = _decode_image(img_buf, shape_hint=(h, w, c))
        out = self._augment(img, rng)  # astype() below always copies
        if pooled is not None:
            pooled.release()
        return out, label[:self.label_width]

    # -- epoch machinery ----------------------------------------------------
    def reset(self):
        # drain in-flight decode tasks, flush finished batches, restart
        self._engine.wait_for_var(self._var)
        try:
            while True:
                self._done_q.get_nowait()
        except queue.Empty:
            pass
        self._inflight = 0
        if self.shuffle:
            self._rng.shuffle(self._epoch_order)
        self._cursor = 0
        for _ in range(self._prefetch):
            self._schedule_batch()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape, np.float32)]

    def _schedule_batch(self):
        """Reserve the next batch window (cursor + RNG advance on the
        consumer thread — deterministic order) and push its decode onto
        the engine."""
        n = len(self._epoch_order)
        if self._cursor >= n:
            return
        hi = self._cursor + self.batch_size
        if hi > n and not self.round_batch:
            return
        sel = self._epoch_order[np.arange(self._cursor, hi) % n].copy()
        pad = max(0, hi - n)
        self._cursor = hi
        seeds = self._rng.randint(0, 2 ** 31 - 1, size=len(sel))

        def task():
            try:
                self._done_q.put(self._decode_batch(sel, pad, seeds))
            except Exception as e:  # surfaced at next()
                self._done_q.put(e)

        self._engine.push(task, mutable_vars=[self._var])
        self._inflight += 1

    def _decode_batch(self, sel, pad, seeds) -> DataBatch:
        """Decode one batch (runs as an engine task; fans across the
        intra-batch thread pool like the reference's OMP ParseChunk)."""
        c, h, w = self.data_shape
        data = np.empty((self.batch_size, c, h, w), dtype=np.float32)
        labels = np.full((self.batch_size, self.label_width),
                         self._label_fill, dtype=np.float32)

        def work(lo, hi_):
            rng = np.random.RandomState(seeds[lo])
            for i in range(lo, hi_):
                img, lab = self._decode_one(self._offsets[sel[i]], rng)
                data[i] = self._postprocess(img)
                labels[i, :lab.shape[0]] = lab

        if self.nthreads == 1 or len(sel) < 2 * self.nthreads:
            work(0, len(sel))
        else:
            chunk = (len(sel) + self.nthreads - 1) // self.nthreads
            threads = [threading.Thread(
                target=work, args=(t * chunk,
                                   min((t + 1) * chunk, len(sel))))
                for t in range(self.nthreads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        label_out = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch(data=[nd_array(data)], label=[nd_array(label_out)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def next(self) -> DataBatch:
        if self._inflight == 0:
            raise StopIteration
        got = self._done_q.get()
        self._inflight -= 1
        self._schedule_batch()  # keep the pipeline `prefetch_buffer` deep
        if isinstance(got, Exception):
            raise got
        return got

    def _postprocess(self, img_chw: np.ndarray) -> np.ndarray:
        return img_chw


class ImageRecordUInt8Iter(ImageRecordIter):
    """uint8 variant — no mean/std normalization (reference
    `ImageRecordUInt8Iter`)."""

    _dtype = np.uint8

    def __init__(self, *args, **kwargs):
        kwargs.pop("mean_r", None), kwargs.pop("std_r", None)
        super(ImageRecordUInt8Iter, self).__init__(*args, **kwargs)
        self.mean = np.zeros((3, 1, 1), np.float32)
        self.std = np.ones((3, 1, 1), np.float32)


ImageRecordIter_v1 = ImageRecordIter  # v1 kept as an alias (same semantics)


class ImageDetRecordIter(ImageRecordIter):
    """Detection variant: variable-length object labels padded to
    label_width (reference `ImageDetRecordIter`,
    `src/io/iter_image_det_recordio.cc`)."""

    def __init__(self, *args, label_pad_width=0, label_pad_value=-1.0,
                 **kwargs):
        self._pad_width = int(label_pad_width)
        self._label_fill = float(label_pad_value)  # -1 = ignore sentinel
        kwargs.setdefault("label_width",
                          self._pad_width if self._pad_width else 6)
        super(ImageDetRecordIter, self).__init__(*args, **kwargs)

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size, self.label_width),
                         np.float32)]
