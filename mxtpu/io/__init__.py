"""`mxtpu.io` — data iterators (reference: `python/mxnet/io/io.py`,
`src/io/*`).

The reference's IO layer is a C++ iterator registry (`src/io/io.cc`)
with a threaded decode pipeline, surfaced in python as `DataIter`
subclasses.  TPU-native design: iterators produce *host* numpy batches
on background threads (decode/augment belongs on host CPU while the
chip runs ahead); the single device transfer happens when the consumer
touches `batch.data` as NDArray.  The C++ pipeline in `src/` (recordio
chunk reader) backs `ImageRecordIter` when built.
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, LibSVMIter, MNISTIter,
                 SimpleIter, create)
from .record_iter import ImageRecordIter, ImageRecordIter_v1, \
    ImageRecordUInt8Iter, ImageDetRecordIter

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "SimpleIter", "ImageRecordIter", "ImageRecordIter_v1",
           "ImageRecordUInt8Iter", "ImageDetRecordIter", "create"]
