"""Core data iterators.

Reference: `python/mxnet/io/io.py` (`DataIter` ABC :178, `NDArrayIter`
:489, `PrefetchingIter` :345, `MXDataIter` :788) and the C++ iterators
behind it (`src/io/iter_mnist.cc`, `iter_csv.cc`, `iter_libsvm.cc`).
Iterators here are pure python/numpy on host threads; batches are
converted to NDArray lazily so a full prefetch pipeline never touches
the device.
"""
from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "SimpleIter", "create"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name/shape/type descriptor (reference `io.py` DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super(DataDesc, cls).__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch(object):
    """A mini-batch: list of data arrays + list of label arrays
    (reference `io.py` DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("DataBatch.data must be a list of arrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise MXNetError("DataBatch.label must be a list of arrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data] if self.data else []
        lshapes = [l.shape for l in self.label] if self.label else []
        return "DataBatch: data shapes: %s label shapes: %s" % (shapes,
                                                                lshapes)


class DataIter(object):
    """Iterator base (reference `io.py:178`).  Subclasses implement
    `next()` raising StopIteration, plus `reset`, `provide_data`,
    `provide_label`."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        # input-wait gauge, nesting-guarded (telemetry.input_wait):
        # nested iterators usually call `.next()` directly, but a
        # wrapper that drives this protocol hop (a DataLoader over a
        # DataIter-backed dataset, a PrefetchingIter) must not make
        # both layers stamp the same wall-clock wait — the guard
        # records only at the outermost level
        from .. import telemetry as _tel

        with _tel.input_wait():
            return self.next()

    def iter_next(self):
        return False

    def getdata(self):
        return None

    def getlabel(self):
        return None

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _as_nd_list(data, allow_empty=False, default_name="data"):
    """Normalize data argument to list of (name, array) like the
    reference's _init_data (`io.py`)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = {default_name: data}
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        v = np.asarray(v)
        if v.dtype == np.float64:
            v = v.astype(np.float32)
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle/pad semantics
    (reference `io.py:489`).

    last_batch_handle: 'pad' (wrap around, report pad count),
    'discard' (drop tail), 'roll_over' (tail carried to next epoch).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super(NDArrayIter, self).__init__(batch_size)
        self.data = _as_nd_list(data, default_name=data_name)
        self.label = _as_nd_list(label, allow_empty=True,
                                 default_name=label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise MXNetError("inconsistent first dims: %s" % k)
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError("bad last_batch_handle %r" % last_batch_handle)
        if last_batch_handle == "roll_over" and \
                0 < self.num_data < batch_size:
            # a carried batch could never fill: epoch 1 would emit
            # nothing and later epochs would break the static shape
            raise MXNetError("roll_over requires num_data >= batch_size"
                             " (%d < %d)" % (self.num_data, batch_size))
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = np.arange(self.num_data)
        self.cursor = -batch_size
        self._cache = None
        self._exhausted = False
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        # reference NDArrayIter.reset (io.py:658): an INCOMPLETE tail
        # batch under roll_over is never emitted — its samples (by
        # their pre-shuffle indices) are carried and concatenated onto
        # the next epoch's first batch
        if self.last_batch_handle == "roll_over" and \
                self.num_data - self.batch_size < self.cursor \
                < self.num_data:
            self._cache = self.idx[self.cursor:].copy()
            self.cursor = self.cursor - self.num_data - self.batch_size
        else:
            self._cache = None
            self.cursor = -self.batch_size
        self._exhausted = False
        if self.shuffle:
            np.random.shuffle(self.idx)

    def iter_next(self):
        if self._exhausted:
            # repeated end-of-data next() calls (e.g. PrefetchingIter's
            # in-flight producers) must not advance the cursor past the
            # roll_over carry window
            return False
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            ok = self.cursor + self.batch_size <= self.num_data
        elif self.last_batch_handle == "roll_over":
            # a carried first batch (cursor < 0) is complete by
            # construction; otherwise only complete batches are emitted
            # — the incomplete tail stops the epoch and gets cached
            ok = self.cursor < 0 or \
                self.cursor + self.batch_size <= self.num_data
        else:
            ok = self.cursor < self.num_data
        self._exhausted = not ok
        return ok

    def _take(self, arrays):
        lo = self.cursor
        hi = self.cursor + self.batch_size
        out = []
        for _, v in arrays:
            if lo < 0:  # roll_over: carried tail + fresh head
                sel = np.concatenate([self._cache, self.idx[:hi]])
            elif hi <= self.num_data:
                sel = self.idx[lo:hi]
            else:  # pad: wrap
                sel = np.concatenate([self.idx[lo:],
                                      self.idx[:hi - self.num_data]])
            out.append(nd_array(v[sel]))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label) if self.label else []

    def getpad(self):
        hi = self.cursor + self.batch_size
        if self.last_batch_handle == "pad" and hi > self.num_data:
            return hi - self.num_data
        if self.last_batch_handle == "roll_over" and self.cursor < 0:
            # reference getpad: carried samples count as pad
            return -self.cursor
        return 0

    def getindex(self):
        hi = self.cursor + self.batch_size
        if self.cursor < 0:  # roll_over carried batch
            return np.concatenate([self._cache, self.idx[:hi]])
        return self.idx[np.arange(self.cursor, hi) % self.num_data]


class SimpleIter(DataIter):
    """Wrap a python generator of DataBatch (used in examples/tests)."""

    def __init__(self, provide_data, provide_label, gen_fn, num_batches):
        super(SimpleIter, self).__init__(provide_data[0].shape[0])
        self.provide_data = provide_data
        self.provide_label = provide_label
        self._gen_fn = gen_fn
        self._num = num_batches
        self._i = 0

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self._num:
            raise StopIteration
        self._i += 1
        data, label = self._gen_fn(self._i - 1)
        return DataBatch(data=[nd_array(d) for d in data],
                         label=[nd_array(l) for l in label],
                         pad=0, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch, resetting the
    underlying iterator as needed (reference `io.py` ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super(ResizeIter, self).__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Prefetch over one or more iterators (reference `io.py:345`,
    backed in C++ by `dmlc::ThreadedIter`, `src/io/iter_prefetcher.h`).

    Producer tasks are scheduled on the dependency engine
    (`mxtpu.engine.get_engine()`), serialized by a mutable engine var:
    under the native ThreadedEngine they run on its C++ worker threads
    and overlap the consumer (decode releases the GIL in numpy/PIL);
    under NaiveEngine (``MXTPU_ENGINE_TYPE=NaiveEngine``) each task
    executes synchronously at schedule time — the reference's
    serialize-everything debug mode.  At most ``prefetch_depth`` batches
    are in flight ahead of the consumer."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super(PrefetchingIter, self).__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        from .. import engine as _engine_mod

        self._engine = _engine_mod.get_engine()
        self._var = self._engine.new_var()
        self._depth = max(1, int(prefetch_depth))
        self._queue: "queue.Queue" = queue.Queue()
        self._seen_end = False
        self._prime()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _produce_one(self):
        """One producer task: pull a batch from every child iterator and
        enqueue it.  Runs on the engine (never raises — end-of-data and
        errors travel through the queue to the consumer)."""
        try:
            batches = [i.next() for i in self.iters]
        except StopIteration:
            self._queue.put(None)
            return
        except Exception as e:  # surface async errors at next()
            self._queue.put(e)
            return
        self._queue.put(batches)

    def _schedule(self):
        self._engine.push(self._produce_one, mutable_vars=[self._var])

    def _prime(self):
        self._seen_end = False
        for _ in range(self._depth):
            self._schedule()

    def reset(self):
        # drain: every scheduled producer task has run once the var is
        # reached, so nothing can enqueue after the flush below
        self._engine.wait_for_var(self._var)
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        for i in self.iters:
            i.reset()
        self._prime()

    def next(self):
        if self._seen_end:
            raise StopIteration
        got = self._queue.get()
        if got is None:
            self._seen_end = True
            raise StopIteration
        if isinstance(got, Exception):
            self._seen_end = True
            raise got
        self._schedule()  # keep `prefetch_depth` batches in flight
        batches = got
        if len(batches) == 1:
            return batches[0]
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([(b.label or []) for b in batches], []),
            pad=max(b.pad or 0 for b in batches),
            provide_data=self.provide_data,
            provide_label=self.provide_label)


class CSVIter(DataIter):
    """Stream a CSV file in fixed-shape rows (reference C++
    `src/io/iter_csv.cc`, registered as CSVIter)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **_):
        super(CSVIter, self).__init__(batch_size)
        self.data_shape = tuple(int(s) for s in
                                (data_shape if isinstance(data_shape,
                                                          (tuple, list))
                                 else eval(str(data_shape))))
        self.label_shape = tuple(int(s) for s in
                                 (label_shape if isinstance(label_shape,
                                                            (tuple, list))
                                  else eval(str(label_shape))))
        data = np.loadtxt(data_csv, delimiter=",",
                          dtype=np.dtype(dtype), ndmin=2)
        data = data.reshape((-1,) + self.data_shape)
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + self.label_shape)
        else:
            label = np.zeros((data.shape[0],) + self.label_shape,
                             dtype=np.float32)
        self._inner = NDArrayIter(
            {"data": data}, {"label": label}, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """LibSVM text format -> CSR batches (reference
    `src/io/iter_libsvm.cc`).  Rows parse straight into CSR triplets —
    nothing densifies, so million-feature datasets cost O(nnz), and each
    batch materializes as a CSRNDArray sliced from the triplet store.
    `num_parts`/`part_index` shard rows for distributed training
    (reference InputSplit)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 num_parts=1, part_index=0, **_):
        super(LibSVMIter, self).__init__(batch_size)
        self.data_shape = tuple(data_shape) if isinstance(
            data_shape, (tuple, list)) else (int(data_shape),)
        self._num_col = int(np.prod(self.data_shape))
        self.round_batch = round_batch

        labels, cols, vals, indptr = [], [], [], [0]
        row_no = 0  # non-empty data rows seen, for shard selection
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                mine = num_parts <= 1 or (row_no % num_parts) == part_index
                row_no += 1
                if not mine:
                    continue
                labels.append([float(parts[0])])
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    cols.append(int(k))
                    vals.append(float(v))
                indptr.append(len(cols))
        if label_libsvm is not None:
            # separate label file: rows pair 1:1 with data rows, so the
            # SAME shard selection applies (multi-column labels kept)
            labels = []
            lrow = 0
            with open(label_libsvm) as f:
                for line in f:
                    if not line.strip():
                        continue
                    mine = num_parts <= 1 or \
                        (lrow % num_parts) == part_index
                    lrow += 1
                    if mine:
                        labels.append([float(t) for t in line.split()])
        self._labels = np.asarray(labels, np.float32) \
            if labels else np.zeros((0, 1), np.float32)
        self._cols = np.asarray(cols, np.int32)
        self._vals = np.asarray(vals, np.float32)
        self._indptr = np.asarray(indptr, np.int64)
        if len(self._labels) != len(self._indptr) - 1:
            raise MXNetError(
                "label rows (%d) != data rows (%d) in %s"
                % (len(self._labels), len(self._indptr) - 1, data_libsvm))
        if row_no == 0:
            raise MXNetError("no rows in %s" % data_libsvm)
        # an EMPTY shard (fewer leftover rows than workers) is legal:
        # this worker simply iterates zero batches
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_col),
                         np.float32)]

    @property
    def provide_label(self):
        lw = self._labels.shape[1] if self._labels.ndim > 1 else 1
        shape = (self.batch_size,) if lw == 1 else (self.batch_size, lw)
        return [DataDesc("softmax_label", shape, np.float32)]

    def reset(self):
        self._cursor = 0

    def _csr_batch(self, lo, hi):
        """CSRNDArray over rows [lo, hi) of the triplet store, padded by
        wrapping (round_batch) so the batch shape is static."""
        from ..ndarray import sparse as _sp

        n = len(self._labels)
        sel = np.arange(lo, hi) % n
        counts = self._indptr[sel + 1] - self._indptr[sel]
        indptr = np.zeros(len(sel) + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        take = np.concatenate([
            np.arange(self._indptr[r], self._indptr[r + 1]) for r in sel]) \
            if len(sel) else np.zeros((0,), np.int64)
        data = self._vals[take]
        cols = self._cols[take]
        csr = _sp.csr_matrix((data, cols, indptr),
                             shape=(len(sel), self._num_col))
        label = self._labels[sel]
        if label.ndim > 1 and label.shape[1] == 1:
            label = label[:, 0]
        return csr, label

    def next(self):
        n = len(self._labels)
        if self._cursor >= n:
            raise StopIteration
        hi = self._cursor + self.batch_size
        if hi > n and not self.round_batch:
            raise StopIteration
        pad = max(0, hi - n)
        csr, label = self._csr_batch(self._cursor, hi)
        self._cursor = hi
        from ..ndarray.ndarray import array as _nd_array

        return DataBatch(data=[csr], label=[_nd_array(label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def _read_idx_file(path):
    """Read an IDX (MNIST) file, gz-transparent."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dtype_code = (magic >> 8) & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dt = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
              0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}[dtype_code]
        data = np.frombuffer(f.read(), dtype=np.dtype(dt).newbyteorder(">"))
        return data.reshape(dims).astype(dt)


class MNISTIter(DataIter):
    """MNIST idx-file iterator (reference `src/io/iter_mnist.cc`).
    Reads local idx/idx.gz files; `flat` yields (batch, 784)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, **_):
        super(MNISTIter, self).__init__(batch_size)
        img = _read_idx_file(image).astype(np.float32) / 255.0
        lab = _read_idx_file(label).astype(np.float32)
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(img.shape[0])
            img, lab = img[order], lab[order]
        self._inner = NDArrayIter({"data": img}, {"softmax_label": lab},
                                  batch_size=batch_size,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


_ITER_REGISTRY = {
    "MNISTIter": MNISTIter,
    "CSVIter": CSVIter,
    "LibSVMIter": LibSVMIter,
    "NDArrayIter": NDArrayIter,
}


def create(name, **kwargs):
    """Create a registered iterator by name (analog of
    `MXDataIterCreateIter`, `src/io/io.cc` registry)."""
    if name not in _ITER_REGISTRY:
        raise MXNetError("unknown data iter %r (have %s)" %
                         (name, sorted(_ITER_REGISTRY)))
    return _ITER_REGISTRY[name](**kwargs)
