"""CachedOp: a reusable compiled graph for Gluon `hybridize()`.

TPU-native re-design of the reference's CachedOp
(`src/imperative/cached_op.{cc,h}`).  The reference caches an NNVM graph
keyed by input signature and replays it through the engine with bulking;
here the traced Symbol lowers to ONE jitted XLA callable (inference) and,
under autograd, to `jax.vjp` over that jitted callable — the forward runs
as a single compiled module, the transpose compiles on first backward, and
the whole CachedOp tapes as a SINGLE autograd node (the reference tapes
`_CachedOp` the same way).  static_alloc/static_shape have no analog: XLA
executables are always statically planned.

BatchNorm-family running stats inside the graph update functionally: the
graph returns new aux values and the CachedOp writes them back into the
aux NDArrays (reference: in-place aux mutation during forward).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from . import autograd as _ag
from . import compile_cache as _cc
from . import health as _health
from . import perf as _perf
from .context import current_context
from .executor import _build_graph_fn
from .ndarray.ndarray import NDArray
from .symbol.symbol import Symbol

__all__ = ["CachedOp"]

_OOM_CALL = _health.oom_scope("cachedop")
_OOM_FUSED = _health.oom_scope("cachedop:fused")

_DATA_NAME_RE = re.compile(r"^data\d*$")


class CachedOp(object):
    """Callable compiled graph.  Inputs are ALL graph arguments in
    `symbol.list_arguments()` order; aux states (running stats) are passed
    via `aux_arrays` and updated in place."""

    def __init__(self, sym: Symbol, flags: Sequence[Tuple[str, Any]] = ()):
        import jax

        self._symbol = sym
        self._flags = dict(flags)
        self._arg_names = sym.list_arguments()
        self._aux_names = sym.list_auxiliary_states()
        self._n_outputs = len(sym.list_outputs())
        from . import amp as _amp

        # the compute-dtype policy _build_graph_fn bakes in below —
        # remembered so a health diagnosis re-executes under the SAME
        # casts this op compiled with
        self._amp_dtype = _amp.get_compute_dtype()

        infer_fn = _build_graph_fn(sym, self._arg_names, self._aux_names,
                                   is_train=False)
        train_fn = _build_graph_fn(sym, self._arg_names, self._aux_names,
                                   is_train=True)

        def fwd_infer(key, *flat):
            n = len(self._arg_names)
            outs, _ = infer_fn(list(flat[:n]), list(flat[n:]), key)
            return tuple(outs)

        def fwd_train(key, *flat):
            n = len(self._arg_names)
            outs, aux_new = train_fn(list(flat[:n]), list(flat[n:]), key)
            return tuple(outs) + tuple(aux_new)

        self._jit_infer = jax.jit(fwd_infer)
        self._jit_train = jax.jit(fwd_train)
        # donated variant for the NON-recording training path: the aux
        # buffers (BN running stats) are dead after the call — __call__
        # writes the returned aux straight back over them — so XLA may
        # update them in place.  The recording path keeps the
        # non-donated jit: there the aux NDArrays also feed the tape,
        # and under jax.vjp tracing donation cannot apply anyway.
        n_args = len(self._arg_names)
        n_aux = len(self._aux_names)
        if n_aux and _cc.donation_enabled():
            self._jit_train_donated = jax.jit(
                fwd_train,
                donate_argnums=tuple(range(1 + n_args, 1 + n_args + n_aux)))
        else:
            self._jit_train_donated = None
        self._infer_fn = infer_fn
        self._train_full_jit = None  # lazy fwd+bwd composite (analysis)
        self._fused_jits: Dict[Tuple[int, ...], Any] = {}
        self._has_rng = any((not n.is_variable) and n.op.needs_rng
                            for n in sym._topo())
        # graphs without RNG ops get one fixed key (avoids a host-side
        # key build + transfer on every hot-path call)
        self._fixed_key = None if self._has_rng else jax.random.PRNGKey(0)
        # compile lifecycle: warmed AOT executables by input signature,
        # seen-signature set for the profiler retrace stats, and the
        # arg slots carrying the (bucketable) batch dim — explicit via
        # the "data_indices" flag (HybridBlock/SymbolBlock set it),
        # else the gluon trace naming convention
        self._aot_infer: Dict[Tuple, Any] = {}
        self._pad_masks: Dict[Tuple, Any] = {}
        self._seen_sigs: set = set()
        flag_idx = self._flags.get("data_indices")
        if flag_idx is not None:
            self._data_idx = [int(i) for i in flag_idx]
        else:
            self._data_idx = [i for i, n in enumerate(self._arg_names)
                              if _DATA_NAME_RE.match(n)]
        # program-inspector registry record (mx.inspect) — keyed by the
        # owning block's name when known (the "program_name" flag
        # HybridBlock sets), else the traced symbol's head name.  A
        # stable key means a REBUILT CachedOp for the same block (whose
        # auto-generated node names shift with the trace counter)
        # accumulates signature history — that is what makes
        # input-structure churn blameable.
        from . import inspect as _insp

        block_name = self._flags.get("program_name")
        self._insp = _insp.program(
            "cachedop", block_name or sym.name,
            arg_names=self._arg_names + self._aux_names, symbol=sym,
            # block names are per-process unique; bare symbol head
            # names (direct CachedOp users) are not
            reuse=bool(block_name))
        # device-memory layout (mx.hbm): the flat call tree is
        # (key, *args, *aux); data slots carry the batch dim, every
        # other arg (and the aux running stats) is model state
        self._insp.mem_layout = {
            "layout": "cachedop",
            "arg_names": list(self._arg_names),
            "aux_names": list(self._aux_names),
            "data_idx": list(self._data_idx),
            "n_outputs": self._n_outputs,
        }

    @property
    def symbol(self) -> Symbol:
        return self._symbol

    def _key(self):
        if self._has_rng:
            from . import random as _rnd

            return _rnd._next_key()
        return self._fixed_key

    def __call__(self, args: Sequence[NDArray],
                 aux_arrays: Sequence[NDArray] = ()):
        with _OOM_CALL:
            return self._call_impl(args, aux_arrays)

    def _call_impl(self, args: Sequence[NDArray],
                   aux_arrays: Sequence[NDArray] = ()):
        if len(args) != len(self._arg_names):
            raise MXNetError("CachedOp expects %d args (%s), got %d"
                             % (len(self._arg_names), self._arg_names,
                                len(args)))
        if len(aux_arrays) != len(self._aux_names):
            raise MXNetError("CachedOp expects %d aux arrays, got %d"
                             % (len(self._aux_names), len(aux_arrays)))
        key = self._key()
        flat = [a._data for a in args] + [a._data for a in aux_arrays]
        ctx = args[0].ctx if args else current_context()
        training = _ag.is_training()
        recording = _ag.is_recording()
        if training and _health.want_context():
            # NaN-provenance context for the gluon Trainer path: hold
            # the NDArray wrappers (aux write-back updates them in
            # place) so a non-finite grad detected at trainer.step can
            # re-execute this dispatch and name the first bad layer.
            # want_context(): stop paying once the per-process
            # diagnosis budget is spent
            _health.register_context("cachedop", self._symbol,
                                     self._arg_names, self._aux_names,
                                     list(args), list(aux_arrays),
                                     key, self._amp_dtype)

        if recording:
            tok = self._track_sig("train" if training else "infer", flat)
            if training:
                def tupled(*xs):
                    return self._jit_train(key, *xs)
            else:
                def tupled(*xs):
                    return self._jit_infer(key, *xs)

            all_nd = list(args) + list(aux_arrays)
            pt0 = _perf.begin()
            outs, node = _ag._record_fn("_CachedOp", tupled, all_nd, flat)
            _perf.end(self._insp.name, "cachedop", pt0, outputs=outs)
            if tok is not None:
                # the recording path runs under jax.vjp, so the train
                # program XLA builds spans forward AND backward — hand
                # the registry a matching fwd+bwd composite, not the
                # forward-only jit (whose cost would understate a
                # train step by the whole backward pass)
                tok.done(self._analysis_train_jit() if training
                         else self._jit_infer,
                         (key,) + tuple(flat))
        else:
            if training:
                tok = self._track_sig("train", flat)
                jit_train = self._jit_train_donated or self._jit_train
                pt0 = _perf.begin()
                outs = jit_train(key, *flat)
                if tok is not None:
                    tok.done(jit_train, (key,) + tuple(flat))
                _perf.end(self._insp.name, "cachedop", pt0, outputs=outs)
            else:
                outs = self._infer_dispatch(key, flat)
            node = None

        n_out = self._n_outputs
        results = []
        for i in range(n_out):
            nd_out = NDArray(outs[i], ctx=ctx, _committed=True)
            if node is not None:
                nd_out._entry = (node, i)
            results.append(nd_out)
        # aux write-back (training graph returns updated aux after outputs)
        if training and len(outs) > n_out:
            for aux_arr, new_val in zip(aux_arrays, outs[n_out:]):
                # detach from tape: aux updates carry no gradient
                aux_arr._set_jax(new_val)
        return results

    # -- compile lifecycle -------------------------------------------------
    def set_data_indices(self, indices: Sequence[int]) -> None:
        """Declare which arg slots carry the batch dim (the slots the
        shape-bucketed dispatch pads).  HybridBlock/SymbolBlock call
        this from their arg mapping; direct users whose data variables
        don't follow the ``data%d`` naming convention should too."""
        self._data_idx = [int(i) for i in indices]
        if self._insp.mem_layout is not None:
            self._insp.mem_layout["data_idx"] = list(self._data_idx)

    def _bucket_spec(self) -> Optional[str]:
        """Per-op flag (`hybridize(shape_buckets=...)`) wins over the
        global MXTPU_SHAPE_BUCKETS policy."""
        spec = self._flags.get("shape_buckets")
        if spec is None:
            return _cc.get_bucket_policy()
        if spec is True:
            return "pow2"
        if spec in (False, "0", "off", "none"):
            return None
        return spec

    def _track_sig(self, kind: str, flat_or_sig, names=None):
        """Retrace accounting — see ``inspect.track_compile`` for the
        contract (None on hit, pending-compile token on a new
        signature).  ``names`` overrides the per-slot arg names when
        the signature's slot order is not ``list_arguments() + aux``
        (the fused dispatch)."""
        from . import inspect as _insp_mod

        sig = flat_or_sig if isinstance(flat_or_sig, tuple) \
            else _cc.sig_of(flat_or_sig)
        return _insp_mod.track_compile(
            self._insp, self._seen_sigs, "cachedop_%s" % kind,
            "cachedop:%s" % kind, kind, sig,
            arg_names=names or (self._arg_names + self._aux_names))

    def _analysis_train_jit(self):
        """Forward+backward composite mirroring what the RECORDING
        train path compiles (``jax.vjp`` over the forward jit), used
        only for the registry's lazy cost/memory analysis — never
        dispatched.  Cotangents are taken for all inputs (the tape
        pulls a subset), so the figures are a faithful slight
        over-approximation of the recorded step."""
        if self._train_full_jit is None:
            import jax
            import jax.numpy as jnp

            fwd = self._jit_train

            def full(key, *flat):
                outs, vjp = jax.vjp(lambda *xs: fwd(key, *xs), *flat)
                ones = tuple(jnp.ones_like(o) for o in outs)
                return outs, vjp(ones)

            self._train_full_jit = jax.jit(full)
        return self._train_full_jit

    def _infer_dispatch(self, key, flat: List[Any]):
        """Inference hot path: bucket-pad ragged batch dims, then serve
        from a warmed AOT executable when one matches, else the jit."""
        from . import profiler as _prof

        spec = self._bucket_spec()
        if spec is not None and self._data_idx:
            sizes = {flat[i].shape[0] for i in self._data_idx
                     if flat[i].ndim > 0}
            if len(sizes) == 1:
                b = sizes.pop()
                bp = _cc.bucket_batch(b, spec)
                if bp != b:
                    mask = self._pad_mask(flat, b, bp)
                    if mask is None:
                        # some output does not track the batch dim (a
                        # reduction over batch would be polluted by pad
                        # rows) — run this shape exact instead
                        _prof.inc_stat("cachedop_bucket_fallback")
                    else:
                        flat = list(flat)
                        for i in self._data_idx:
                            flat[i] = _cc.pad_leading(flat[i], bp)
                        _prof.inc_stat("cachedop_bucket_pad")
                        outs = self._run_infer(key, flat)
                        return tuple(o[:b] if m else o
                                     for o, m in zip(outs, mask))
        return self._run_infer(key, flat)

    def _run_infer(self, key, flat):
        from . import profiler as _prof

        sig = _cc.sig_of(flat)
        compiled = self._aot_infer.get(sig)
        if compiled is not None:
            _prof.inc_stat("cachedop_aot_hit")
            self._insp.hit()
            pt0 = _perf.begin()
            outs = compiled(key, *flat)
            _perf.end(self._insp.name, "cachedop", pt0, outputs=outs)
            return outs
        tok = self._track_sig("infer", sig)
        pt0 = _perf.begin()
        outs = self._jit_infer(key, *flat)
        if tok is not None:
            tok.done(self._jit_infer, (key,) + tuple(flat))
        _perf.end(self._insp.name, "cachedop", pt0, outputs=outs)
        return outs

    def _pad_mask(self, flat, b: int, bp: int):
        """Per-output slice mask for padding b -> bp, from shape
        inference (cached).  None = padding unsafe for this graph/shape
        (an output doesn't carry the batch dim)."""
        shapes_u = tuple(tuple(v.shape) for v in flat[:len(self._arg_names)])
        key = (b, bp, shapes_u)
        if key in self._pad_masks:
            return self._pad_masks[key]
        data = set(self._data_idx)
        shapes_p = tuple((bp,) + s[1:] if i in data else s
                         for i, s in enumerate(shapes_u))
        mask = _cc.batch_output_mask(self._symbol, self._arg_names,
                                     shapes_u, shapes_p)
        if mask is not None and not all(mask):
            mask = None
        self._pad_masks[key] = mask
        return mask

    @staticmethod
    def _spec(item, default_dtype) -> Tuple[Tuple[int, ...], np.dtype]:
        if hasattr(item, "shape") and hasattr(item, "dtype"):
            return (tuple(item.shape), np.dtype(item.dtype))
        if isinstance(item, (tuple, list)) and len(item) == 2 \
                and isinstance(item[0], (tuple, list)):
            return (tuple(item[0]), np.dtype(item[1]))
        return (tuple(item), np.dtype(default_dtype))

    def warmup(self, args: Sequence[Any], aux: Sequence[Any] = (),
               dtype="float32"):
        """AOT-compile the inference program for one input signature
        via ``jit(...).lower().compile()`` — no execution, and calls
        matching the signature dispatch straight to the stored
        executable (zero further compiles).  ``args``/``aux`` entries
        are arrays, shape tuples (``dtype`` fills in), or
        ``(shape, dtype)`` pairs, in `symbol.list_arguments()` /
        `list_auxiliary_states()` order.  Call once per serving bucket
        to pre-build the whole bucket set.  Returns self."""
        import jax

        from . import profiler as _prof

        specs = [self._spec(a, dtype) for a in args]
        aux_specs = [self._spec(a, "float32") for a in aux]
        if len(specs) != len(self._arg_names) or \
                len(aux_specs) != len(self._aux_names):
            raise MXNetError(
                "warmup expects %d args + %d aux shapes, got %d + %d"
                % (len(self._arg_names), len(self._aux_names),
                   len(specs), len(aux_specs)))
        # key must match the dispatch path's _cc.sig_of (dtype OBJECTS)
        sig = tuple((s, d) for s, d in specs + aux_specs)
        if sig in self._aot_infer:
            return self
        k = jax.random.PRNGKey(0)
        structs = [jax.ShapeDtypeStruct(k.shape, k.dtype)] + \
            [jax.ShapeDtypeStruct(s, d) for s, d in specs + aux_specs]
        self._aot_infer[sig] = _cc.aot_compile(self._jit_infer, structs,
                                               program=self._insp,
                                               kind="infer")
        _prof.inc_stat("cachedop_warmup")
        return self

    def call_fused(self, args: Sequence[NDArray],
                   aux_arrays: Sequence[NDArray] = (),
                   stacked_idx: Sequence[int] = ()):
        with _OOM_FUSED:
            return self._call_fused_impl(args, aux_arrays, stacked_idx)

    def _call_fused_impl(self, args: Sequence[NDArray],
                         aux_arrays: Sequence[NDArray] = (),
                         stacked_idx: Sequence[int] = ()):
        """Forward-only inference over K batches in ONE device program.

        Each arg whose index is in ``stacked_idx`` carries a leading K
        dimension; the compiled program `lax.scan`s the graph over the
        stacks while the remaining args (weights) are passed once.  The
        inference analog of FusedTrainLoop: on a remote PJRT client the
        per-dispatch round trip (~tens of ms) otherwise dominates
        small-batch scoring (reference amortizes per-op scheduling via
        engine bulking instead, `src/engine/threaded_engine.h:411`).
        Returns stacked (K, ...) output NDArrays.  Aux stats are read,
        never written (inference semantics); autograd is not supported
        through this path."""
        import jax
        from jax import lax

        if _ag.is_recording():
            raise MXNetError("call_fused is inference-only; do not call "
                             "it under autograd.record()")
        stacked = tuple(sorted(int(i) for i in stacked_idx))
        if not stacked:
            raise MXNetError("call_fused needs at least one stacked arg")
        n = len(self._arg_names)
        cached = self._fused_jits.get(stacked)
        if cached is None:
            fixed = tuple(i for i in range(n) if i not in stacked)
            infer_fn = self._infer_fn

            def program(key, stack_vals, fixed_vals, aux_vals):
                def body(carry, xs):
                    step, data_vals = xs
                    full = [None] * n
                    for j, i in enumerate(stacked):
                        full[i] = data_vals[j]
                    for j, i in enumerate(fixed):
                        full[i] = fixed_vals[j]
                    outs, _unused_aux = infer_fn(
                        full, list(aux_vals),
                        jax.random.fold_in(key, step))
                    return carry, tuple(outs)

                import jax.numpy as jnp

                K = stack_vals[0].shape[0]
                _, outs = lax.scan(
                    body, 0, (jnp.arange(K), tuple(stack_vals)),
                    # XLA:CPU barely parallelizes inside loop bodies
                    # (same rationale as FusedTrainLoop's unroll)
                    unroll=(jax.default_backend() == "cpu"))
                return outs

            cached = (jax.jit(program), fixed)
            self._fused_jits[stacked] = cached
        jit_program, fixed = cached
        K = args[stacked[0]].shape[0]
        for i in stacked:
            if args[i].shape[0] != K:
                raise MXNetError("stacked args disagree on leading K")
        stack_vals = tuple(args[i]._data for i in stacked)
        fixed_vals = [args[i]._data for i in fixed]
        aux_vals = [a._data for a in aux_arrays]
        # the fused scan program is a compile site like any other:
        # retrace accounting + blame + the compile fault barrier
        tok = self._track_sig(
            "fused_infer", list(stack_vals) + fixed_vals + aux_vals,
            names=[self._arg_names[i] for i in stacked] +
                  [self._arg_names[i] for i in fixed] + self._aux_names)
        key = self._key()
        pt0 = _perf.begin()
        outs = jit_program(key, stack_vals, fixed_vals, aux_vals)
        if tok is not None:
            tok.done(jit_program, (key, stack_vals, fixed_vals, aux_vals))
        _perf.end(self._insp.name, "cachedop", pt0, outputs=outs, n=K)
        ctx = args[stacked[0]].ctx
        return [NDArray(o, ctx=ctx, _committed=True) for o in outs]
