"""CachedOp: a reusable compiled graph for Gluon `hybridize()`.

TPU-native re-design of the reference's CachedOp
(`src/imperative/cached_op.{cc,h}`).  The reference caches an NNVM graph
keyed by input signature and replays it through the engine with bulking;
here the traced Symbol lowers to ONE jitted XLA callable (inference) and,
under autograd, to `jax.vjp` over that jitted callable — the forward runs
as a single compiled module, the transpose compiles on first backward, and
the whole CachedOp tapes as a SINGLE autograd node (the reference tapes
`_CachedOp` the same way).  static_alloc/static_shape have no analog: XLA
executables are always statically planned.

BatchNorm-family running stats inside the graph update functionally: the
graph returns new aux values and the CachedOp writes them back into the
aux NDArrays (reference: in-place aux mutation during forward).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from . import autograd as _ag
from .context import current_context
from .executor import _build_graph_fn
from .ndarray.ndarray import NDArray
from .symbol.symbol import Symbol

__all__ = ["CachedOp"]


class CachedOp(object):
    """Callable compiled graph.  Inputs are ALL graph arguments in
    `symbol.list_arguments()` order; aux states (running stats) are passed
    via `aux_arrays` and updated in place."""

    def __init__(self, sym: Symbol, flags: Sequence[Tuple[str, Any]] = ()):
        import jax

        self._symbol = sym
        self._flags = dict(flags)
        self._arg_names = sym.list_arguments()
        self._aux_names = sym.list_auxiliary_states()
        self._n_outputs = len(sym.list_outputs())

        infer_fn = _build_graph_fn(sym, self._arg_names, self._aux_names,
                                   is_train=False)
        train_fn = _build_graph_fn(sym, self._arg_names, self._aux_names,
                                   is_train=True)

        def fwd_infer(key, *flat):
            n = len(self._arg_names)
            outs, _ = infer_fn(list(flat[:n]), list(flat[n:]), key)
            return tuple(outs)

        def fwd_train(key, *flat):
            n = len(self._arg_names)
            outs, aux_new = train_fn(list(flat[:n]), list(flat[n:]), key)
            return tuple(outs) + tuple(aux_new)

        self._jit_infer = jax.jit(fwd_infer)
        self._jit_train = jax.jit(fwd_train)
        self._has_rng = any((not n.is_variable) and n.op.needs_rng
                            for n in sym._topo())
        # graphs without RNG ops get one fixed key (avoids a host-side
        # key build + transfer on every hot-path call)
        self._fixed_key = None if self._has_rng else jax.random.PRNGKey(0)

    @property
    def symbol(self) -> Symbol:
        return self._symbol

    def _key(self):
        if self._has_rng:
            from . import random as _rnd

            return _rnd._next_key()
        return self._fixed_key

    def __call__(self, args: Sequence[NDArray],
                 aux_arrays: Sequence[NDArray] = ()):
        if len(args) != len(self._arg_names):
            raise MXNetError("CachedOp expects %d args (%s), got %d"
                             % (len(self._arg_names), self._arg_names,
                                len(args)))
        if len(aux_arrays) != len(self._aux_names):
            raise MXNetError("CachedOp expects %d aux arrays, got %d"
                             % (len(self._aux_names), len(aux_arrays)))
        key = self._key()
        flat = [a._data for a in args] + [a._data for a in aux_arrays]
        ctx = args[0].ctx if args else current_context()
        training = _ag.is_training()
        recording = _ag.is_recording()

        if recording:
            if training:
                def tupled(*xs):
                    return self._jit_train(key, *xs)
            else:
                def tupled(*xs):
                    return self._jit_infer(key, *xs)

            all_nd = list(args) + list(aux_arrays)
            outs, node = _ag._record_fn("_CachedOp", tupled, all_nd, flat)
        else:
            if training:
                outs = self._jit_train(key, *flat)
            else:
                outs = self._jit_infer(key, *flat)
            node = None

        n_out = self._n_outputs
        results = []
        for i in range(n_out):
            nd_out = NDArray(outs[i], ctx=ctx, _committed=True)
            if node is not None:
                nd_out._entry = (node, i)
            results.append(nd_out)
        # aux write-back (training graph returns updated aux after outputs)
        if training and len(outs) > n_out:
            for aux_arr, new_val in zip(aux_arrays, outs[n_out:]):
                # detach from tape: aux updates carry no gradient
                aux_arr._set_jax(new_val)
        return results
