"""CachedOp: a reusable compiled graph for Gluon `hybridize()`.

TPU-native re-design of the reference's CachedOp
(`src/imperative/cached_op.{cc,h}`).  The reference caches an NNVM graph
keyed by input signature and replays it through the engine with bulking;
here the traced Symbol lowers to ONE jitted XLA callable (inference) and,
under autograd, to `jax.vjp` over that jitted callable — the forward runs
as a single compiled module, the transpose compiles on first backward, and
the whole CachedOp tapes as a SINGLE autograd node (the reference tapes
`_CachedOp` the same way).  static_alloc/static_shape have no analog: XLA
executables are always statically planned.

BatchNorm-family running stats inside the graph update functionally: the
graph returns new aux values and the CachedOp writes them back into the
aux NDArrays (reference: in-place aux mutation during forward).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from . import autograd as _ag
from .context import current_context
from .executor import _build_graph_fn
from .ndarray.ndarray import NDArray
from .symbol.symbol import Symbol

__all__ = ["CachedOp"]


class CachedOp(object):
    """Callable compiled graph.  Inputs are ALL graph arguments in
    `symbol.list_arguments()` order; aux states (running stats) are passed
    via `aux_arrays` and updated in place."""

    def __init__(self, sym: Symbol, flags: Sequence[Tuple[str, Any]] = ()):
        import jax

        self._symbol = sym
        self._flags = dict(flags)
        self._arg_names = sym.list_arguments()
        self._aux_names = sym.list_auxiliary_states()
        self._n_outputs = len(sym.list_outputs())

        infer_fn = _build_graph_fn(sym, self._arg_names, self._aux_names,
                                   is_train=False)
        train_fn = _build_graph_fn(sym, self._arg_names, self._aux_names,
                                   is_train=True)

        def fwd_infer(key, *flat):
            n = len(self._arg_names)
            outs, _ = infer_fn(list(flat[:n]), list(flat[n:]), key)
            return tuple(outs)

        def fwd_train(key, *flat):
            n = len(self._arg_names)
            outs, aux_new = train_fn(list(flat[:n]), list(flat[n:]), key)
            return tuple(outs) + tuple(aux_new)

        self._jit_infer = jax.jit(fwd_infer)
        self._jit_train = jax.jit(fwd_train)
        self._infer_fn = infer_fn
        self._fused_jits: Dict[Tuple[int, ...], Any] = {}
        self._has_rng = any((not n.is_variable) and n.op.needs_rng
                            for n in sym._topo())
        # graphs without RNG ops get one fixed key (avoids a host-side
        # key build + transfer on every hot-path call)
        self._fixed_key = None if self._has_rng else jax.random.PRNGKey(0)

    @property
    def symbol(self) -> Symbol:
        return self._symbol

    def _key(self):
        if self._has_rng:
            from . import random as _rnd

            return _rnd._next_key()
        return self._fixed_key

    def __call__(self, args: Sequence[NDArray],
                 aux_arrays: Sequence[NDArray] = ()):
        if len(args) != len(self._arg_names):
            raise MXNetError("CachedOp expects %d args (%s), got %d"
                             % (len(self._arg_names), self._arg_names,
                                len(args)))
        if len(aux_arrays) != len(self._aux_names):
            raise MXNetError("CachedOp expects %d aux arrays, got %d"
                             % (len(self._aux_names), len(aux_arrays)))
        key = self._key()
        flat = [a._data for a in args] + [a._data for a in aux_arrays]
        ctx = args[0].ctx if args else current_context()
        training = _ag.is_training()
        recording = _ag.is_recording()

        if recording:
            if training:
                def tupled(*xs):
                    return self._jit_train(key, *xs)
            else:
                def tupled(*xs):
                    return self._jit_infer(key, *xs)

            all_nd = list(args) + list(aux_arrays)
            outs, node = _ag._record_fn("_CachedOp", tupled, all_nd, flat)
        else:
            if training:
                outs = self._jit_train(key, *flat)
            else:
                outs = self._jit_infer(key, *flat)
            node = None

        n_out = self._n_outputs
        results = []
        for i in range(n_out):
            nd_out = NDArray(outs[i], ctx=ctx, _committed=True)
            if node is not None:
                nd_out._entry = (node, i)
            results.append(nd_out)
        # aux write-back (training graph returns updated aux after outputs)
        if training and len(outs) > n_out:
            for aux_arr, new_val in zip(aux_arrays, outs[n_out:]):
                # detach from tape: aux updates carry no gradient
                aux_arr._set_jax(new_val)
        return results

    def call_fused(self, args: Sequence[NDArray],
                   aux_arrays: Sequence[NDArray] = (),
                   stacked_idx: Sequence[int] = ()):
        """Forward-only inference over K batches in ONE device program.

        Each arg whose index is in ``stacked_idx`` carries a leading K
        dimension; the compiled program `lax.scan`s the graph over the
        stacks while the remaining args (weights) are passed once.  The
        inference analog of FusedTrainLoop: on a remote PJRT client the
        per-dispatch round trip (~tens of ms) otherwise dominates
        small-batch scoring (reference amortizes per-op scheduling via
        engine bulking instead, `src/engine/threaded_engine.h:411`).
        Returns stacked (K, ...) output NDArrays.  Aux stats are read,
        never written (inference semantics); autograd is not supported
        through this path."""
        import jax
        from jax import lax

        if _ag.is_recording():
            raise MXNetError("call_fused is inference-only; do not call "
                             "it under autograd.record()")
        stacked = tuple(sorted(int(i) for i in stacked_idx))
        if not stacked:
            raise MXNetError("call_fused needs at least one stacked arg")
        n = len(self._arg_names)
        cached = self._fused_jits.get(stacked)
        if cached is None:
            fixed = tuple(i for i in range(n) if i not in stacked)
            infer_fn = self._infer_fn

            def program(key, stack_vals, fixed_vals, aux_vals):
                def body(carry, xs):
                    step, data_vals = xs
                    full = [None] * n
                    for j, i in enumerate(stacked):
                        full[i] = data_vals[j]
                    for j, i in enumerate(fixed):
                        full[i] = fixed_vals[j]
                    outs, _unused_aux = infer_fn(
                        full, list(aux_vals),
                        jax.random.fold_in(key, step))
                    return carry, tuple(outs)

                import jax.numpy as jnp

                K = stack_vals[0].shape[0]
                _, outs = lax.scan(
                    body, 0, (jnp.arange(K), tuple(stack_vals)),
                    # XLA:CPU barely parallelizes inside loop bodies
                    # (same rationale as FusedTrainLoop's unroll)
                    unroll=(jax.default_backend() == "cpu"))
                return outs

            cached = (jax.jit(program), fixed)
            self._fused_jits[stacked] = cached
        jit_program, fixed = cached
        K = args[stacked[0]].shape[0]
        for i in stacked:
            if args[i].shape[0] != K:
                raise MXNetError("stacked args disagree on leading K")
        stack_vals = tuple(args[i]._data for i in stacked)
        fixed_vals = [args[i]._data for i in fixed]
        aux_vals = [a._data for a in aux_arrays]
        outs = jit_program(self._key(), stack_vals, fixed_vals, aux_vals)
        ctx = args[stacked[0]].ctx
        return [NDArray(o, ctx=ctx, _committed=True) for o in outs]
