"""Shared test infrastructure (reference: `python/mxnet/test_utils.py`,
2,029 LoC).

The reference's test strategy (SURVEY.md §4) rests on a small set of
helpers used by every per-op test: `assert_almost_equal`,
`check_numeric_gradient` (finite differences vs autograd),
`check_symbolic_forward/backward`, `rand_ndarray`, `default_context`.
This module provides the same surface for the TPU build; the
cross-device ground truth (reference: CPU-vs-GPU `check_consistency`,
`tests/python/gpu/test_operator_gpu.py`) becomes CPU-vs-TPU.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array as nd_array

__all__ = [
    "default_context", "set_default_context", "default_dtype",
    "assert_almost_equal", "almost_equal", "same", "rand_shape_2d",
    "rand_shape_3d", "rand_shape_nd", "rand_ndarray", "random_arrays",
    "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "numeric_grad",
    "simple_forward", "create_2d_tensor",
]

_default_ctx: Optional[Context] = None


def default_context() -> Context:
    """Context for tests; honors MXNET_TEST_DEVICE=cpu|tpu
    (analog of the reference's default_context switched by env)."""
    global _default_ctx
    if _default_ctx is not None:
        return _default_ctx
    dev = os.environ.get("MXNET_TEST_DEVICE", "")
    if dev == "cpu":
        return cpu()
    if dev.startswith("tpu"):
        from .context import tpu
        return tpu()
    return current_context()


def set_default_context(ctx: Context):
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


def _asnumpy(x) -> np.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b) -> bool:
    return np.array_equal(_asnumpy(a), _asnumpy(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False) -> bool:
    return np.allclose(_asnumpy(a), _asnumpy(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _asnumpy(a), _asnumpy(b)
    if a_np.shape != b_np.shape:
        raise AssertionError(
            "shape mismatch %s %s vs %s %s" %
            (names[0], a_np.shape, names[1], b_np.shape))
    if not np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        diff = np.abs(a_np - b_np)
        denom = np.abs(b_np) + atol
        rel = diff / np.where(denom == 0, 1, denom)
        idx = np.unravel_index(np.argmax(rel), rel.shape) if rel.size else ()
        raise AssertionError(
            "%s and %s differ: max abs %.3e max rel %.3e at %s "
            "(%r vs %r), rtol=%g atol=%g" %
            (names[0], names[1], float(diff.max()), float(rel.max()), idx,
             a_np[idx] if idx != () else a_np,
             b_np[idx] if idx != () else b_np, rtol, atol))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, distribution="uniform"):
    """Random NDArray, optionally sparse (reference rand_ndarray incl.
    sparse, `python/mxnet/test_utils.py`)."""
    dtype = dtype or default_dtype()
    ctx = ctx or default_context()
    if stype == "default":
        if distribution == "uniform":
            arr = np.random.uniform(-1.0, 1.0, size=shape)
        else:
            arr = np.random.normal(size=shape)
        return nd_array(arr.astype(dtype), ctx=ctx)
    from .ndarray import sparse as _sp
    density = 0.1 if density is None else density
    arr = np.random.uniform(-1.0, 1.0, size=shape).astype(dtype)
    mask = np.random.uniform(size=shape) < density
    arr = arr * mask
    dense = nd_array(arr, ctx=ctx)
    return dense.tostype(stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(default_dtype()) if s else
              np.asarray(np.random.randn()).astype(default_dtype())
              for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Bind a symbol with the given numpy inputs and run forward once."""
    ctx = ctx or default_context()
    shapes = {k: v.shape for k, v in inputs.items()}
    exe = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
    for k, v in inputs.items():
        exe.arg_dict[k][:] = v
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    if len(outputs) == 1:
        return outputs[0]
    return outputs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central finite differences d(sum(outputs))/d(arg) per argument
    (reference numeric_grad used by check_numeric_gradient)."""
    grads = {}
    for name, arr in location.items():
        base = arr.copy()
        g = np.zeros_like(base, dtype=np.float64)
        flat = base.reshape(-1)
        gflat = g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            executor.arg_dict[name][:] = base
            fp = sum(float(o.asnumpy().astype(np.float64).sum())
                     for o in executor.forward(is_train=use_forward_train))
            flat[i] = orig - eps
            executor.arg_dict[name][:] = base
            fm = sum(float(o.asnumpy().astype(np.float64).sum())
                     for o in executor.forward(is_train=use_forward_train))
            flat[i] = orig
            gflat[i] = (fp - fm) / (2.0 * eps)
        executor.arg_dict[name][:] = base
        grads[name] = g
    return grads


def _location_dict(sym, location):
    if isinstance(location, dict):
        return {k: _asnumpy(v).astype(np.float64) for k, v in
                location.items()}
    args = sym.list_arguments()
    return {k: _asnumpy(v).astype(np.float64)
            for k, v in zip(args, location)}


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, ctx=None, dtype=np.float64):
    """Finite-difference check of autograd gradients (reference
    check_numeric_gradient, `python/mxnet/test_utils.py`)."""
    ctx = ctx or default_context()
    loc = _location_dict(sym, location)
    loc32 = {k: v.astype(np.float32) for k, v in loc.items()}
    grad_nodes = grad_nodes or list(loc.keys())
    grad_req = {k: ("write" if k in grad_nodes else "null") for k in loc}

    shapes = {k: v.shape for k, v in loc.items()}
    exe = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
    for k, v in loc32.items():
        exe.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k][:] = _asnumpy(v)
    outputs = exe.forward(is_train=True)
    ograds = [nd_array(np.ones(o.shape, dtype=np.float32), ctx=ctx)
              for o in outputs]
    exe.backward(ograds)
    sym_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    num_grads = numeric_grad(exe, {k: loc32[k].copy() for k in grad_nodes},
                             eps=numeric_eps)
    atol = atol if atol is not None else max(numeric_eps * 10, 1e-4)
    for k in grad_nodes:
        assert_almost_equal(sym_grads[k], num_grads[k].astype(np.float32),
                            rtol=rtol, atol=atol,
                            names=("autograd[%s]" % k, "numeric[%s]" % k))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None, equal_nan=False):
    """Forward the bound symbol and compare against expected numpy outputs
    (reference check_symbolic_forward)."""
    ctx = ctx or default_context()
    loc = _location_dict(sym, location)
    shapes = {k: v.shape for k, v in loc.items()}
    exe = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
    for k, v in loc.items():
        exe.arg_dict[k][:] = v.astype(np.float32)
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k][:] = _asnumpy(v)
    outputs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for i, (out, exp) in enumerate(zip(outputs, expected)):
        assert_almost_equal(out, exp, rtol=rtol, atol=atol,
                            names=("output[%d]" % i, "expected[%d]" % i),
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-4, atol=1e-5, aux_states=None,
                            grad_req="write", ctx=None):
    """Backward the bound symbol with the given head gradients and compare
    input gradients against expected (reference check_symbolic_backward)."""
    ctx = ctx or default_context()
    loc = _location_dict(sym, location)
    shapes = {k: v.shape for k, v in loc.items()}
    if isinstance(grad_req, str):  # explicit dict: inputs DO get grads here
        grad_req = {k: grad_req for k in loc}
    exe = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
    for k, v in loc.items():
        exe.arg_dict[k][:] = v.astype(np.float32)
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k][:] = _asnumpy(v)
    exe.forward(is_train=True)
    ograds = [nd_array(_asnumpy(g).astype(np.float32), ctx=ctx)
              for g in (out_grads if isinstance(out_grads, (list, tuple))
                        else [out_grads])]
    exe.backward(ograds)
    if isinstance(expected, dict):
        exp_items = expected.items()
    else:
        exp_items = zip(sym.list_arguments(), expected)
    grads = {}
    for k, exp in exp_items:
        grads[k] = exe.grad_dict[k].asnumpy()
        assert_almost_equal(grads[k], exp, rtol=rtol, atol=atol,
                            names=("grad[%s]" % k, "expected[%s]" % k))
    return grads


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      rtol=1e-4, atol=1e-4):
    """Cross-device consistency: run the same symbol on every context and
    compare all outputs/gradients against the first (the reference's
    CPU-vs-GPU ground truth, `tests/python/gpu/test_operator_gpu.py`;
    here CPU-vs-TPU)."""
    results = []
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx")
        shapes = spec
        req = ({k: grad_req for k in shapes}
               if isinstance(grad_req, str) and grad_req != "null"
               else grad_req)
        exe = sym.simple_bind(ctx=ctx, grad_req=req, **shapes)
        if not results:
            np.random.seed(0)
            init = {k: np.random.normal(size=v.shape, scale=scale)
                    .astype(np.float32) for k, v in exe.arg_dict.items()}
        for k, v in exe.arg_dict.items():
            v[:] = init[k]
        outputs = exe.forward(is_train=grad_req != "null")
        if grad_req != "null":
            exe.backward([nd_array(np.ones(o.shape, dtype=np.float32),
                                   ctx=ctx) for o in outputs])
        results.append(exe)
    ref = results[0]
    for other in results[1:]:
        for i, (a, b) in enumerate(zip(ref.outputs, other.outputs)):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=("ctx0.out%d" % i, "ctxN.out%d" % i))
        if grad_req != "null":
            for k in ref.grad_dict:
                if ref.grad_dict[k] is None:
                    continue
                assert_almost_equal(ref.grad_dict[k], other.grad_dict[k],
                                    rtol=rtol, atol=atol,
                                    names=("ctx0.grad[%s]" % k,
                                           "ctxN.grad[%s]" % k))
    return results


def create_2d_tensor(rows, columns, dtype=np.int64):
    data = np.arange(0, rows, dtype=dtype).reshape(rows, 1)
    return nd_array(np.broadcast_to(data, (rows, columns)).copy())
