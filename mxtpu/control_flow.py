"""User-facing control flow: `foreach`, `while_loop`, `cond` over
NDArrays AND Symbols.

Reference: `python/mxnet/symbol/contrib.py` + `python/mxnet/ndarray/
contrib.py` building the `_foreach/_while_loop/_cond` subgraph ops of
`src/operator/control_flow.cc:491-547`.

TPU-native behavior: on Symbols the body/cond callables are traced with
placeholder variables into subgraph Symbols attached to ONE registered
node (`mxtpu/ops/control_flow.py`), which lowers to `lax.scan` /
`lax.while_loop` / `lax.cond` inside the same fused XLA module as the
rest of the graph — structured XLA control flow instead of the
reference's per-iteration nested-CachedOp dispatch.  On NDArrays the
loop runs imperatively (plain Python, autograd-taped), matching the
reference's imperative fallback.

Free variables: the callables may close over outer Symbols; any
non-placeholder leaf variable of the traced subgraph is wired into the
node as an input resolved by NAME at bind time (weights etc.).
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

from .base import MXNetError

__all__ = ["foreach", "while_loop", "cond"]


def _is_symbol(x):
    from .symbol.symbol import Symbol

    return isinstance(x, Symbol)


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _sub_io(subgraph, placeholders):
    """Split subgraph arguments into placeholder locs and free-variable
    locs; return (sub_args, locs_per_placeholder, free_names,
    free_locs, aux_names)."""
    sub_args = subgraph.list_arguments()
    pos = {n: i for i, n in enumerate(sub_args)}
    ph_locs = []
    used = set()
    for name in placeholders:
        loc = pos.get(name, -1)
        ph_locs.append(loc)
        if loc >= 0:
            used.add(loc)
    free = [(n, i) for i, n in enumerate(sub_args) if i not in used]
    return (sub_args, ph_locs, [n for n, _ in free],
            [i for _, i in free], subgraph.list_auxiliary_states())


def _outer_vars(names, aux_names=()):
    """Create outer-graph variables resolved by name at bind time."""
    from .symbol.symbol import Variable

    out = []
    for n in names:
        v = Variable(n)
        if n in aux_names:
            v._outputs[0][0].is_aux = True
        out.append(v)
    return out


def _node(op_name, inputs, attrs, name):
    from .symbol.register import invoke_symbol

    return invoke_symbol(op_name, inputs, attrs, name=name)


# ---------------------------------------------------------------------------
# foreach
# ---------------------------------------------------------------------------

def foreach(body: Callable, data, init_states, name: str = "foreach"):
    """Run `body(x_t, states) -> (out_t, new_states)` over axis 0 of
    `data` (a (list of) NDArray/Symbol), carrying `states`.

    Returns (outputs, final_states) — outputs stacked along a new
    axis 0 (reference `sym.contrib.foreach`)."""
    data_list = _as_list(data)
    states = _as_list(init_states)
    data_is_list = isinstance(data, (list, tuple))
    states_is_list = isinstance(init_states, (list, tuple))

    if data_list and _is_symbol(data_list[0]):
        return _foreach_sym(body, data_list, states, data_is_list,
                            states_is_list, name)

    # imperative: plain Python loop (taped by autograd like any op)
    from .ndarray import stack

    n = data_list[0].shape[0]
    outs_steps = None
    single_out = False
    for t in range(n):
        xs = [d[t] for d in data_list]
        out, states = body(xs if data_is_list else xs[0],
                           states if states_is_list else states[0])
        states = _as_list(states)
        single_out = not isinstance(out, (list, tuple))
        out = _as_list(out)
        if outs_steps is None:
            outs_steps = [[] for _ in out]
        for slot, o in zip(outs_steps, out):
            slot.append(o)
    outs = [stack(*slot, axis=0) for slot in (outs_steps or [])]
    return (outs[0] if single_out else outs,
            states if states_is_list else states[0])


def _foreach_sym(body, data_list, states, data_is_list, states_is_list,
                 name):
    from .symbol.symbol import Variable
    from .symbol import Group

    data_vars = [Variable("_cf_%s_data%d" % (name, i))
                 for i in range(len(data_list))]
    state_vars = [Variable("_cf_%s_state%d" % (name, i))
                  for i in range(len(states))]
    out, new_states = body(
        data_vars if data_is_list else data_vars[0],
        state_vars if states_is_list else state_vars[0])
    single_out = not isinstance(out, (list, tuple))
    outs = _as_list(out)
    new_states = _as_list(new_states)
    if len(new_states) != len(states):
        raise MXNetError("foreach body returned %d states, expected %d"
                         % (len(new_states), len(states)))
    subgraph = Group(outs + new_states)

    ph_names = [v.name for v in data_vars] + [v.name for v in state_vars]
    sub_args, ph_locs, free_names, free_locs, aux_names = \
        _sub_io(subgraph, ph_names)
    nd_ = len(data_vars)
    data_locs = ph_locs[:nd_]
    state_locs = ph_locs[nd_:]
    if any(l < 0 for l in data_locs):
        raise MXNetError("foreach body must use the data argument")

    # the op aligns the scan carry with the state list positionally, so
    # every state var must appear in the subgraph
    if any(l < 0 for l in state_locs):
        raise MXNetError("every foreach state must be used by the body "
                         "(unused states: pass them through explicitly)")
    inputs = (data_list + list(states)
              + _outer_vars(free_names, aux_names)
              + _outer_vars(aux_names, aux_names))
    attrs = dict(subgraph=subgraph, sub_args=tuple(sub_args),
                 sub_aux=tuple(aux_names),
                 data_locs=tuple(data_locs),
                 state_locs=tuple(state_locs),
                 free_locs=tuple(free_locs),
                 num_out_data=len(outs), num_states=len(new_states))
    node = _node("_foreach", inputs, attrs, name)
    out_syms = [node[i] for i in range(len(outs))]
    st_syms = [node[len(outs) + i] for i in range(len(new_states))]
    return (out_syms[0] if single_out else out_syms,
            st_syms if states_is_list else st_syms[0])


# ---------------------------------------------------------------------------
# while_loop
# ---------------------------------------------------------------------------

def while_loop(cond: Callable, func: Callable, loop_vars,
               max_iterations: int, name: str = "while_loop"):
    """`while cond(*loop_vars): step_out, loop_vars = func(*loop_vars)`
    bounded by max_iterations; step outputs are stacked into
    (max_iterations, ...) buffers, rows past the trip count zero
    (reference `sym.contrib.while_loop` semantics).

    Returns (outputs, final_loop_vars)."""
    lv = _as_list(loop_vars)
    if lv and _is_symbol(lv[0]):
        return _while_loop_sym(cond, func, lv, max_iterations, name)

    import numpy as np

    from .ndarray import array, stack, zeros

    outs_steps = None
    single_out = False
    n_iter = 0
    vars_ = lv
    while n_iter < max_iterations and \
            bool(np.asarray(cond(*vars_).asnumpy()).reshape(())):
        out, vars_ = func(*vars_)
        vars_ = _as_list(vars_)
        single_out = not isinstance(out, (list, tuple))
        out = _as_list(out)
        if outs_steps is None:
            outs_steps = [[] for _ in out]
        for slot, o in zip(outs_steps, out):
            slot.append(o)
        n_iter += 1
    if outs_steps is None:
        # zero iterations: probe shapes with one (discarded) func call
        out, _ = func(*lv)
        single_out = not isinstance(out, (list, tuple))
        outs = [zeros((max_iterations,) + o.shape, dtype=o.dtype)
                for o in _as_list(out)]
    else:
        outs = []
        for slot in outs_steps:
            stacked = stack(*slot, axis=0)
            if n_iter < max_iterations:
                pad = zeros((max_iterations - n_iter,) + slot[0].shape,
                            dtype=slot[0].dtype)
                from .ndarray import concat

                stacked = concat(stacked, pad, dim=0)
            outs.append(stacked)
    return (outs[0] if single_out else outs, vars_)


def _while_loop_sym(cond, func, lv, max_iterations, name):
    from .symbol.symbol import Variable
    from .symbol import Group

    n_states = len(lv)
    cond_vars = [Variable("_cf_%s_cv%d" % (name, i))
                 for i in range(n_states)]
    body_vars = [Variable("_cf_%s_bv%d" % (name, i))
                 for i in range(n_states)]

    pred = cond(*cond_vars)
    cond_graph = Group([pred])
    out, new_vars = func(*body_vars)
    single_out = not isinstance(out, (list, tuple))
    outs = _as_list(out)
    new_vars = _as_list(new_vars)
    if len(new_vars) != n_states:
        raise MXNetError("while_loop func returned %d loop_vars, "
                         "expected %d" % (len(new_vars), n_states))
    body_graph = Group(outs + new_vars)

    cond_args, cond_ph, cfree_names, cfree_locs, caux = _sub_io(
        cond_graph, [v.name for v in cond_vars])
    body_args, body_ph, bfree_names, bfree_locs, baux = _sub_io(
        body_graph, [v.name for v in body_vars])
    if any(l < 0 for l in body_ph):
        raise MXNetError("every while_loop loop_var must be used by func")
    aux_names = list(dict.fromkeys(list(caux) + list(baux)))

    inputs = (list(lv) + _outer_vars(cfree_names, aux_names)
              + _outer_vars(bfree_names, aux_names)
              + _outer_vars(aux_names, aux_names))
    # cond may not read every loop var: cond_state_idx maps its used
    # placeholder slots back to loop-var positions
    used_cond_states = tuple(i for i, l in enumerate(cond_ph) if l >= 0)
    attrs = dict(cond_graph=cond_graph, cond_args=tuple(cond_args),
                 body_graph=body_graph, body_args=tuple(body_args),
                 sub_aux=tuple(aux_names),
                 state_locs_cond=tuple(cond_ph[i]
                                       for i in used_cond_states),
                 free_locs_cond=tuple(cfree_locs),
                 state_locs_body=tuple(body_ph),
                 free_locs_body=tuple(bfree_locs),
                 cond_state_idx=used_cond_states,
                 n_states=n_states, num_out_data=len(outs),
                 num_states=n_states,
                 max_iterations=int(max_iterations))
    node = _node("_while_loop", inputs, attrs, name)
    out_syms = [node[i] for i in range(len(outs))]
    st_syms = [node[len(outs) + i] for i in range(n_states)]
    return (out_syms[0] if single_out else out_syms, st_syms)


# ---------------------------------------------------------------------------
# cond
# ---------------------------------------------------------------------------

def cond(pred, then_func: Callable, else_func: Callable,
         name: str = "cond"):
    """`then_func() if pred else else_func()` — both branches must
    produce matching shapes/dtypes (reference `sym.contrib.cond`)."""
    if _is_symbol(pred):
        return _cond_sym(pred, then_func, else_func, name)

    import numpy as np

    branch = then_func if bool(
        np.asarray(pred.asnumpy()).reshape(())) else else_func
    return branch()


def _cond_sym(pred, then_func, else_func, name):
    from .symbol import Group

    then_out = then_func()
    single_out = not isinstance(then_out, (list, tuple))
    then_outs = _as_list(then_out)
    else_outs = _as_list(else_func())
    if len(then_outs) != len(else_outs):
        raise MXNetError("cond branches disagree on output count")
    then_graph = Group(then_outs)
    else_graph = Group(else_outs)

    then_args, _, tfree, tlocs, taux = _sub_io(then_graph, [])
    else_args, _, efree, elocs, eaux = _sub_io(else_graph, [])
    aux_names = list(dict.fromkeys(list(taux) + list(eaux)))

    inputs = ([pred] + _outer_vars(tfree, aux_names)
              + _outer_vars(efree, aux_names)
              + _outer_vars(aux_names, aux_names))
    attrs = dict(then_graph=then_graph, then_args=tuple(then_args),
                 else_graph=else_graph, else_args=tuple(else_args),
                 sub_aux=tuple(aux_names),
                 n_then_free=len(tfree),
                 num_outputs=len(then_outs))
    node = _node("_cond", inputs, attrs, name)
    outs = [node[i] for i in range(len(then_outs))]
    return outs[0] if single_out else outs


# ---------------------------------------------------------------------------
# Shape-inference metadata: free-variable (weight) shapes are solved by
# running the SUBGRAPH's own partial shape inference — the analog of the
# reference's subgraph infer-shape forwarding in control_flow.cc.
# ---------------------------------------------------------------------------

def _solve_subgraph(sub, sub_args, known, free_locs, base):
    try:
        arg_shapes, _, aux_shapes = sub.infer_shape_partial(**known)
    except Exception:
        return {}, []
    solved = {}
    for k, loc in enumerate(free_locs):
        if arg_shapes[loc] is not None:
            solved[base + k] = tuple(arg_shapes[loc])
    return solved, list(aux_shapes)


def _foreach_shapes(in_shapes, attrs):
    sub_args = list(attrs["sub_args"])
    data_locs = attrs["data_locs"]
    state_locs = attrs["state_locs"]
    free_locs = attrs["free_locs"]
    nd_, ns_, nf_ = len(data_locs), len(state_locs), len(free_locs)
    known = {}
    for i, loc in enumerate(data_locs):
        if in_shapes[i] is not None:
            known[sub_args[loc]] = tuple(in_shapes[i][1:])
    for j, loc in enumerate(state_locs):
        if in_shapes[nd_ + j] is not None:
            known[sub_args[loc]] = tuple(in_shapes[nd_ + j])
    solved, aux_shapes = _solve_subgraph(
        attrs["subgraph"], sub_args, known, free_locs, nd_ + ns_)
    for a, shp in enumerate(aux_shapes):
        if shp is not None:
            solved[nd_ + ns_ + nf_ + a] = tuple(shp)
    return solved


def _while_loop_shapes(in_shapes, attrs):
    ns_ = attrs["n_states"]
    cfree = attrs["free_locs_cond"]
    bfree = attrs["free_locs_body"]
    cidx = attrs.get("cond_state_idx")
    if cidx is None:
        cidx = tuple(range(ns_))
    known_c = {}
    for slot, loc in zip(cidx, attrs["state_locs_cond"]):
        if in_shapes[slot] is not None:
            known_c[attrs["cond_args"][loc]] = tuple(in_shapes[slot])
    known_b = {}
    for j, loc in enumerate(attrs["state_locs_body"]):
        if in_shapes[j] is not None:
            known_b[attrs["body_args"][loc]] = tuple(in_shapes[j])
    solved, _ = _solve_subgraph(attrs["cond_graph"], attrs["cond_args"],
                                known_c, cfree, ns_)
    s2, aux_shapes = _solve_subgraph(attrs["body_graph"],
                                     attrs["body_args"], known_b, bfree,
                                     ns_ + len(cfree))
    solved.update(s2)
    base = ns_ + len(cfree) + len(bfree)
    for a, shp in enumerate(aux_shapes):
        if shp is not None:
            solved[base + a] = tuple(shp)
    return solved


def _cond_shapes(in_shapes, attrs):
    ntf = attrs["n_then_free"]
    tfree = tuple(range(len(attrs["then_args"])))
    efree = tuple(range(len(attrs["else_args"])))
    solved, _ = _solve_subgraph(attrs["then_graph"], attrs["then_args"],
                                {}, tfree, 1)
    s2, aux_shapes = _solve_subgraph(attrs["else_graph"],
                                     attrs["else_args"], {}, efree,
                                     1 + ntf)
    solved.update(s2)
    base = 1 + ntf + len(efree)
    for a, shp in enumerate(aux_shapes):
        if shp is not None:
            solved[base + a] = tuple(shp)
    return solved


def _register_meta():
    from .symbol.op_meta import OpMeta, register_meta

    register_meta("_foreach", OpMeta([], variadic=True,
                                     param_shapes=_foreach_shapes))
    register_meta("_while_loop", OpMeta([], variadic=True,
                                        param_shapes=_while_loop_shapes))
    register_meta("_cond", OpMeta([], variadic=True,
                                  param_shapes=_cond_shapes))


_register_meta()
