"""ctypes bindings for the native runtime (src/ → libmxtpu_runtime.so).

The analog of the reference's ctypes library load (`python/mxnet/base.py`
_load_lib → libmxnet.so).  The library is optional: `available()` is
False when it hasn't been built (`make -C src`), and every consumer
falls back to its pure-python path.  Search order: $MXTPU_NATIVE_LIB,
then src/build/libmxtpu_runtime.so next to the package.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

AsyncFnType = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)
ProducerFnType = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p,
    ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
    ctypes.POINTER(ctypes.c_uint64))


def _lib_path() -> str:
    env = os.environ.get("MXTPU_NATIVE_LIB")
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(here, "src", "build", "libmxtpu_runtime.so")


def build(quiet: bool = True) -> bool:
    """Build the native library in place (`make -C src`)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(here, "src")
    if not os.path.exists(os.path.join(src, "Makefile")):
        return False
    res = subprocess.run(["make", "-C", src],
                         capture_output=quiet, text=True)
    global _TRIED
    _TRIED = False  # allow re-probe
    return res.returncode == 0


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    # signatures
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    lib.MXTPUEngineCreate.restype = ctypes.c_void_p
    lib.MXTPUEngineCreate.argtypes = [ctypes.c_int]
    lib.MXTPUEngineFree.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineNewVar.restype = ctypes.c_uint64
    lib.MXTPUEngineNewVar.argtypes = [ctypes.c_void_p]
    lib.MXTPUEnginePushAsync.restype = ctypes.c_int
    lib.MXTPUEnginePushAsync.argtypes = [
        ctypes.c_void_p, AsyncFnType, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int]
    lib.MXTPUEngineWaitForVar.restype = ctypes.c_int
    lib.MXTPUEngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPUEngineWaitForAll.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineVarVersion.restype = ctypes.c_uint64
    lib.MXTPUEngineVarVersion.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPUEngineNumOutstanding.restype = ctypes.c_int64
    lib.MXTPUEngineNumOutstanding.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineDeleteVar.argtypes = [ctypes.c_void_p, ctypes.c_uint64]

    lib.MXTPUStorageAlloc.restype = ctypes.c_void_p
    lib.MXTPUStorageAlloc.argtypes = [ctypes.c_size_t]
    lib.MXTPUStorageFree.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.MXTPUStorageDirectFree.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.MXTPUStorageReleaseAll.argtypes = []
    lib.MXTPUStoragePooledBytes.restype = ctypes.c_size_t
    lib.MXTPUStorageUsedBytes.restype = ctypes.c_size_t

    lib.MXTPURecordWriterCreate.restype = ctypes.c_void_p
    lib.MXTPURecordWriterCreate.argtypes = [ctypes.c_char_p]
    lib.MXTPURecordWriterWrite.restype = ctypes.c_int
    lib.MXTPURecordWriterWrite.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p,
                                           ctypes.c_uint64]
    lib.MXTPURecordWriterTell.restype = ctypes.c_int64
    lib.MXTPURecordWriterTell.argtypes = [ctypes.c_void_p]
    lib.MXTPURecordWriterClose.argtypes = [ctypes.c_void_p]
    lib.MXTPURecordReaderCreate.restype = ctypes.c_void_p
    lib.MXTPURecordReaderCreate.argtypes = [ctypes.c_char_p]
    lib.MXTPURecordReaderRead.restype = ctypes.c_int
    lib.MXTPURecordReaderRead.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.MXTPURecordReaderSeek.restype = ctypes.c_int
    lib.MXTPURecordReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPURecordReaderTell.restype = ctypes.c_int64
    lib.MXTPURecordReaderTell.argtypes = [ctypes.c_void_p]
    lib.MXTPURecordReaderClose.argtypes = [ctypes.c_void_p]
    lib.MXTPUBufferFree.argtypes = [ctypes.POINTER(ctypes.c_char)]

    lib.MXTPUPrefetcherCreate.restype = ctypes.c_void_p
    lib.MXTPUPrefetcherCreate.argtypes = [ProducerFnType, ctypes.c_void_p,
                                          ctypes.c_int]
    lib.MXTPUPrefetcherNext.restype = ctypes.c_int
    lib.MXTPUPrefetcherNext.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.MXTPUPrefetcherFree.argtypes = [ctypes.c_void_p]
    lib.MXTPURecordPrefetcherCreate.restype = ctypes.c_void_p
    lib.MXTPURecordPrefetcherCreate.argtypes = [ctypes.c_char_p,
                                                ctypes.c_int]
    lib.MXTPURecordPrefetcherFree.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return _LIB


def get_lib() -> Optional[ctypes.CDLL]:
    return _load()


def available() -> bool:
    return _load() is not None


class PooledBuffer(object):
    """A host byte buffer drawn from the native storage pool
    (`src/storage.cc` size-bucketed free-lists — the reference's
    `storage::CPUDeviceStorage` pooling, GPUPooledStorageManager analog
    `src/storage/pooled_storage_manager.h`).  Used by the IO path to
    stage recordio payloads without a malloc per record.

    Returns memory to the pool on `release()` (or GC).  Use
    `memoryview(buf)` / `buf.view` for zero-copy reads into it.
    """

    __slots__ = ("_ptr", "_size", "view")

    def __init__(self, size: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime not built")
        self._size = int(size)
        self._ptr = lib.MXTPUStorageAlloc(self._size)
        if not self._ptr:
            raise MemoryError("MXTPUStorageAlloc(%d) failed" % size)
        self.view = (ctypes.c_char * self._size).from_address(self._ptr)

    def release(self):
        if self._ptr:
            lib = get_lib()
            if lib is not None:
                lib.MXTPUStorageFree(ctypes.c_void_p(self._ptr), self._size)
            self._ptr = None
            self.view = None

    def __len__(self):
        return self._size

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass
