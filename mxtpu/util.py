"""Small user-facing utilities (reference `python/mxnet/util.py`)."""
import os

__all__ = ["makedirs"]


def makedirs(d):
    """Create directory recursively; no error if it exists (reference
    `util.py:23` — predates exist_ok, kept for API parity)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)
