"""Network visualization (reference: `python/mxnet/visualization.py`).

`print_summary` walks the symbol graph and prints a layer table with
output shapes and parameter counts; `plot_network` renders a graphviz
digraph when graphviz is installed.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .base import MXNetError
from .symbol.symbol import Symbol, _topo_order

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol: Symbol, shape: Optional[Dict] = None,
                  line_length: int = 120, positions=(.44, .64, .74, 1.)):
    """Layer-table summary (reference `visualization.py:print_summary`)."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    lines = []

    def print_row(vals, pos):
        line = ""
        for i, v in enumerate(vals):
            line += str(v)
            line = line[:pos[i]]
            line += " " * (pos[i] - len(line))
        lines.append(line)

    print_row(fields, positions)
    lines.append("=" * line_length)

    total_params = 0
    nodes = _topo_order(symbol._outputs)
    for node in nodes:
        if node.is_variable and node.name in ("data",):
            out_shape = shape.get(node.name) if shape else None
            print_row([f"{node.name}(null)", out_shape or "", 0, ""],
                      positions)
            lines.append("_" * line_length)
            continue
        if node.is_variable:
            continue
        op_name = node.op.name
        out_name = "%s_output" % node.name
        out_shape = shape_dict.get(out_name, "")
        # params = product of this node's variable-input shapes
        cur_param = 0
        pred = []
        provided = set(shape or ())
        for inode, _ in node.inputs:
            if inode.is_variable and inode.name in provided:
                pred.append(inode.name)
            elif inode.is_variable and inode.name != "data":
                vshape = shape_dict.get("%s_output" % inode.name)
                if vshape is None and shape is not None:
                    # variable outputs are listed under their own name
                    vshape = shape_dict.get(inode.name)
                if vshape:
                    cur_param += int(np.prod(vshape))
            elif not inode.is_variable:
                pred.append(inode.name)
            elif inode.name == "data":
                pred.append(inode.name)
        total_params += cur_param
        print_row(["%s(%s)" % (node.name, op_name), out_shape, cur_param,
                   ",".join(pred)], positions)
        lines.append("_" * line_length)
    lines.append("Total params: %d" % total_params)
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


def plot_network(symbol: Symbol, title: str = "plot",
                 save_format: str = "pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights: bool = True):
    """Graphviz digraph of the network (reference
    `visualization.py:plot_network`); requires the `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires graphviz")
    dot = Digraph(name=title, format=save_format)
    nodes = _topo_order(symbol._outputs)
    for node in nodes:
        if node.is_variable:
            if not hide_weights or node.name == "data":
                dot.node(str(id(node)), label=node.name, shape="oval")
            continue
        dot.node(str(id(node)), label="%s\n%s" % (node.name, node.op.name),
                 shape="box")
        for inode, _ in node.inputs:
            if inode.is_variable and hide_weights and inode.name != "data":
                continue
            dot.edge(str(id(inode)), str(id(node)))
    return dot
