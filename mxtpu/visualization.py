"""Network visualization (reference: `python/mxnet/visualization.py`).

`print_summary` walks the symbol graph and prints a layer table with
output shapes, parameter counts and — when input shapes are given — a
per-layer FLOPs column from XLA's own cost analysis (the same cost
model the `mx.inspect` program registry reports); when a bound
compiled program exists for the symbol, the footer cites the
registry's whole-program figures.  `plot_network` renders a graphviz
digraph when graphviz is installed.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .base import MXNetError
from .symbol.symbol import Symbol, _topo_order

__all__ = ["print_summary", "plot_network"]


def _node_flops(node, shape_dict, provided):
    """XLA FLOP estimate for one op node, from its input shapes (the
    per-layer source of `print_summary`'s FLOPs column)."""
    from . import inspect as _insp

    in_shapes, in_dtypes = [], []
    for inode, idx in node.inputs:
        if inode.is_variable:
            s = provided.get(inode.name) or \
                shape_dict.get(inode.name) or \
                shape_dict.get("%s_output" % inode.name)
        else:
            key = "%s_output" % inode.name
            s = shape_dict.get(key)
            if s is None and inode.num_outputs() > 1:
                s = shape_dict.get("%s_output%d" % (inode.name, idx))
        if s is None:
            return None
        in_shapes.append(tuple(s))
        in_dtypes.append("float32")
    return _insp.op_flops(node, in_shapes, in_dtypes)


def print_summary(symbol: Symbol, shape: Optional[Dict] = None,
                  line_length: int = 120, positions=None,
                  flops: str = "auto"):
    """Layer-table summary (reference `visualization.py:print_summary`).

    With ``shape`` given and ``flops`` not ``False``, a per-layer
    FLOPs column is added (XLA cost analysis per op, memoized); when a
    compiled program is registered for this symbol in ``mx.inspect``,
    the footer reports the whole-program FLOPs / peak-memory figures
    from the registry.  A caller-provided ``positions`` is always
    honored: a 5-tuple lays out the FLOPs table, a 4-tuple keeps the
    caller's classic 4-column layout (FLOPs column omitted)."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    want_flops = bool(shape_dict) and flops not in (False, "off", "0")
    if want_flops and positions is not None and len(positions) != 5:
        want_flops = False  # honor an explicit 4-column layout
    if want_flops:
        if positions is None:
            positions = (.38, .54, .64, .80, 1.)
        fields = ["Layer (type)", "Output Shape", "Param #", "FLOPs",
                  "Previous Layer"]
    else:
        if positions is None:
            positions = (.44, .64, .74, 1.)
        fields = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]
    positions = [int(line_length * p) for p in positions]

    lines = []

    def print_row(vals, pos):
        line = ""
        for i, v in enumerate(vals):
            line += str(v)
            line = line[:pos[i]]
            line += " " * (pos[i] - len(line))
        lines.append(line)

    print_row(fields, positions)
    lines.append("=" * line_length)

    total_params = 0
    total_flops = 0.0
    nodes = _topo_order(symbol._outputs)
    for node in nodes:
        if node.is_variable and node.name in ("data",):
            out_shape = shape.get(node.name) if shape else None
            row = [f"{node.name}(null)", out_shape or "", 0, ""]
            if want_flops:
                row.insert(3, "")
            print_row(row, positions)
            lines.append("_" * line_length)
            continue
        if node.is_variable:
            continue
        op_name = node.op.name
        out_name = "%s_output" % node.name
        out_shape = shape_dict.get(out_name, "")
        # params = product of this node's variable-input shapes
        cur_param = 0
        pred = []
        provided = set(shape or ())
        for inode, _ in node.inputs:
            if inode.is_variable and inode.name in provided:
                pred.append(inode.name)
            elif inode.is_variable and inode.name != "data":
                vshape = shape_dict.get("%s_output" % inode.name)
                if vshape is None and shape is not None:
                    # variable outputs are listed under their own name
                    vshape = shape_dict.get(inode.name)
                if vshape:
                    cur_param += int(np.prod(vshape))
            elif not inode.is_variable:
                pred.append(inode.name)
            elif inode.name == "data":
                pred.append(inode.name)
        total_params += cur_param
        row = ["%s(%s)" % (node.name, op_name), out_shape, cur_param,
               ",".join(pred)]
        if want_flops:
            nf = _node_flops(node, shape_dict, dict(shape or {}))
            if nf is None:
                row.insert(3, "?")
            else:
                total_flops += nf
                row.insert(3, "%d" % int(nf))
        print_row(row, positions)
        lines.append("_" * line_length)
    lines.append("Total params: %d" % total_params)
    if want_flops:
        lines.append("Total FLOPs (XLA per-op forward estimate): %d"
                     % int(total_flops))
        from . import inspect as _insp

        prog = _insp.find_for_symbol(symbol)
        if prog is not None and prog.latest_sig() is not None:
            a = prog.latest_sig().analyze()
            if a.get("flops"):
                lines.append(
                    "Compiled program %s [%s]: FLOPs %d, peak memory "
                    "%.2f MB" % (prog.name, prog.latest_sig().kind,
                                 int(a["flops"]),
                                 a.get("peak_bytes", 0) / 2**20))
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


def plot_network(symbol: Symbol, title: str = "plot",
                 save_format: str = "pdf", shape=None, dtype=None,
                 node_attrs=None, hide_weights: bool = True):
    """Graphviz digraph of the network (reference
    `visualization.py:plot_network`); requires the `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires graphviz")
    dot = Digraph(name=title, format=save_format)
    nodes = _topo_order(symbol._outputs)
    for node in nodes:
        if node.is_variable:
            if not hide_weights or node.name == "data":
                dot.node(str(id(node)), label=node.name, shape="oval")
            continue
        dot.node(str(id(node)), label="%s\n%s" % (node.name, node.op.name),
                 shape="box")
        for inode, _ in node.inputs:
            if inode.is_variable and hide_weights and inode.name != "data":
                continue
            dot.edge(str(id(inode)), str(id(node)))
    return dot
