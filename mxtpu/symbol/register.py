"""Symbol composer codegen — `sym.FullyConnected(data=x, num_hidden=...)`.

Analog of the reference's symbol-side op codegen
(`python/mxnet/symbol/register.py`): every registered op gets a composer
that accepts Symbol inputs positionally or by input name, auto-creates
missing input variables ("fc1_weight", "bn0_moving_mean"...), and returns
a new Symbol — `MXSymbolCreateAtomicSymbol`+Compose collapsed into one
step.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..base import MXNetError, _Null
from ..ops import registry as _reg
from . import op_meta as _meta_mod
from .symbol import NameManager, Symbol, SymbolNode, Variable


def invoke_symbol(op_name: str, input_syms: Sequence[Symbol],
                  attrs: Dict[str, Any], name: Optional[str] = None) -> Symbol:
    opdef = _reg.get_op(op_name)
    attrs = {k: v for k, v in attrs.items()
             if v is not None and v is not _Null}
    hint = opdef.name.lower().lstrip("_")
    node_name = NameManager.current().get(name, hint)
    entries = []
    for s in input_syms:
        if len(s._outputs) != 1:
            raise MXNetError("op inputs must be single-output symbols")
        entries.append(s._outputs[0])
    node = SymbolNode(opdef, node_name, attrs, entries)
    return Symbol([(node, i)
                   for i in range(opdef.n_visible_outputs(attrs))])


def _make_symbol_function(opdef):
    meta_mod = _meta_mod

    def fn(*args, name=None, attr=None, **kwargs):
        meta = meta_mod.get_meta(opdef)
        hint = opdef.name.lower().lstrip("_")
        node_name = NameManager.current().get(name, hint)

        # split kwargs into symbol inputs vs op attrs
        sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        attrs = {k: v for k, v in kwargs.items()
                 if not isinstance(v, Symbol) and v is not None
                 and v is not _Null}

        for a in args:
            if not isinstance(a, Symbol):
                raise MXNetError(
                    "positional argument %r to %s is not a Symbol; operator "
                    "attributes must be passed by keyword (e.g. "
                    "num_hidden=..., act_type=...)" % (a, opdef.name))
        sym_args = list(args)
        if meta.variadic and not sym_kwargs:
            inputs = sym_args
        else:
            input_names = meta.input_names(attrs)
            # the reference accepts `data=` for any op's first input
            # (FListInputNames defaults to "data"); honor that here
            if "data" in sym_kwargs and input_names \
                    and "data" not in input_names and not sym_args \
                    and input_names[0] not in sym_kwargs:
                sym_kwargs[input_names[0]] = sym_kwargs.pop("data")
            inputs = []
            for i, in_name in enumerate(input_names):
                if i < len(sym_args):
                    inputs.append(sym_args[i])
                elif in_name in sym_kwargs:
                    inputs.append(sym_kwargs.pop(in_name))
                else:
                    v = Variable("%s_%s" % (node_name, in_name))
                    if i in meta.aux_indices:
                        v._outputs[0][0].is_aux = True
                    inputs.append(v)
            if sym_kwargs:
                raise MXNetError("unknown symbol inputs %s for op %s"
                                 % (list(sym_kwargs), opdef.name))
        entries = [s._outputs[0] for s in inputs]
        node = SymbolNode(opdef, node_name, attrs, entries)
        if attr:
            node.ext_attrs.update({k: str(v) for k, v in attr.items()})
        return Symbol([(node, i)
                       for i in range(opdef.n_visible_outputs(attrs))])

    fn.__name__ = opdef.name
    fn.__doc__ = opdef.doc
    fn.__module__ = "mxtpu.symbol"
    return fn


def _init_symbol_module(target_module):
    seen = set()
    for op_name, opdef in _reg._OP_REGISTRY.items():
        if op_name in seen:
            continue
        seen.add(op_name)
        setattr(target_module, op_name, _make_symbol_function(opdef))
    # ops registered after this module initialized (late imports, user
    # registrations) still get composers
    _reg.add_post_register_hook(
        lambda name, od: setattr(target_module, name,
                                 _make_symbol_function(od)))
