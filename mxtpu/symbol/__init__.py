"""`mxtpu.sym` — symbolic API (reference: `python/mxnet/symbol/`)."""
import sys as _sys
import types as _types

from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     NameManager, AttrScope)
from . import op_meta  # noqa: F401
from . import register as _register_mod

_this = _sys.modules[__name__]
_register_mod._init_symbol_module(_this)

# zeros/ones convenience (reference sym.zeros)
zeros = getattr(_this, "_zeros")
ones = getattr(_this, "_ones")

# `sym.contrib` / `sym.linalg` sub-namespaces
contrib = _types.ModuleType(__name__ + ".contrib")
linalg = _types.ModuleType(__name__ + ".linalg")
for _name in dir(_this):
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], getattr(_this, _name))
    elif _name.startswith("_linalg_"):
        setattr(linalg, _name[len("_linalg_"):], getattr(_this, _name))
_sys.modules[contrib.__name__] = contrib
_sys.modules[linalg.__name__] = linalg


def _alias_late_op(_name, _opdef):
    # keep the prefix-stripped sub-namespaces in sync with ops
    # registered after this package imported
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], getattr(_this, _name))
    elif _name.startswith("_linalg_"):
        setattr(linalg, _name[len("_linalg_"):], getattr(_this, _name))


from ..ops import registry as _reg  # noqa: E402

_reg.add_post_register_hook(_alias_late_op)
