"""Per-op metadata for symbolic composition.

The reference's NNVM registry carries FListInputNames and FInferShape per
op (`include/mxnet/op_attr_types.h`), which is what lets
`Symbol.simple_bind` auto-create weight/bias variables and solve their
shapes from the data shape (`src/executor/infer_graph_attr_pass.cc`).

Here forward shape inference is free (`jax.eval_shape` on the op's JAX
function), so this table only carries what JAX can't know:
  * input names (for auto-created variables: "fc1_weight"...)
  * which inputs are auxiliary states (BatchNorm moving stats)
  * backward parameter-shape solving: given the data shape + attrs,
    produce the parameter shapes.
Ops not listed default to all-data inputs named from the function
signature.
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.registry import OpDef


class OpMeta(object):
    def __init__(self, input_names, aux_indices=(), param_shapes=None,
                 variadic=False):
        # input_names: list[str] | callable(attrs)->list[str]
        self._input_names = input_names
        self.aux_indices = tuple(aux_indices)
        # param_shapes: callable(data_shapes: list[Optional[tuple]], attrs)
        #               -> dict{input_index: shape}
        self.param_shapes = param_shapes
        self.variadic = variadic

    def input_names(self, attrs) -> List[str]:
        if callable(self._input_names):
            return self._input_names(attrs)
        return list(self._input_names)


_META: Dict[str, OpMeta] = {}


def register_meta(op_name: str, meta: OpMeta):
    _META[op_name] = meta


def get_meta(opdef: OpDef) -> OpMeta:
    m = _META.get(opdef.name)
    if m is not None:
        return m
    # derive from the python signature: positional params are inputs
    fn = opdef.fn
    sig = inspect.signature(fn)
    names = []
    variadic = False
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            variadic = True
            continue
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD) \
                and p.default is inspect.Parameter.empty:
            if p.name == "key" and opdef.needs_rng:
                continue
            names.append(p.name)
    m = OpMeta(names, variadic=variadic)
    _META[opdef.name] = m
    return m


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


# ---------------------------------------------------------------------------
# Layer ops with learnable parameters
# ---------------------------------------------------------------------------

def _fc_inputs(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else \
        ["data", "weight", "bias"]


def _fc_shapes(shapes, attrs):
    data = shapes[0]
    nh = int(attrs["num_hidden"])
    if data is None:
        return {}
    in_units = _prod(data[1:]) if attrs.get("flatten", True) else data[-1]
    out = {1: (nh, in_units)}
    if not attrs.get("no_bias"):
        out[2] = (nh,)
    return out


register_meta("FullyConnected", OpMeta(_fc_inputs, param_shapes=_fc_shapes))


def _conv_inputs(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else \
        ["data", "weight", "bias"]


def _conv_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    nf = int(attrs["num_filter"])
    g = int(attrs.get("num_group", 1))
    kernel = tuple(attrs["kernel"])
    out = {1: (nf, data[1] // g) + kernel}
    if not attrs.get("no_bias"):
        out[2] = (nf,)
    return out


register_meta("Convolution", OpMeta(_conv_inputs, param_shapes=_conv_shapes))
register_meta("Convolution_v1", OpMeta(_conv_inputs, param_shapes=_conv_shapes))


def _deconv_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    nf = int(attrs["num_filter"])
    g = int(attrs.get("num_group", 1))
    kernel = tuple(attrs["kernel"])
    out = {1: (data[1], nf // g) + kernel}
    if not attrs.get("no_bias", True):
        out[2] = (nf,)
    return out


def _deconv_inputs(attrs):
    return ["data", "weight"] if attrs.get("no_bias", True) else \
        ["data", "weight", "bias"]


register_meta("Deconvolution", OpMeta(_deconv_inputs,
                                      param_shapes=_deconv_shapes))


def _bn_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    axis = int(attrs.get("axis", 1))
    c = data[axis % len(data)]
    return {1: (c,), 2: (c,), 3: (c,), 4: (c,)}


register_meta("BatchNorm", OpMeta(
    ["data", "gamma", "beta", "moving_mean", "moving_var"],
    aux_indices=(3, 4), param_shapes=_bn_shapes))
register_meta("BatchNorm_v1", OpMeta(
    ["data", "gamma", "beta", "moving_mean", "moving_var"],
    aux_indices=(3, 4), param_shapes=_bn_shapes))
register_meta("_contrib_SyncBatchNorm", OpMeta(
    ["data", "gamma", "beta", "moving_mean", "moving_var"],
    aux_indices=(3, 4), param_shapes=_bn_shapes))


def _ln_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    axis = int(attrs.get("axis", -1))
    c = data[axis % len(data)]
    return {1: (c,), 2: (c,)}


register_meta("LayerNorm", OpMeta(["data", "gamma", "beta"],
                                  param_shapes=_ln_shapes))


def _in_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return {}
    return {1: (data[1],), 2: (data[1],)}


register_meta("InstanceNorm", OpMeta(["data", "gamma", "beta"],
                                     param_shapes=_in_shapes))


def _emb_shapes(shapes, attrs):
    return {1: (int(attrs["input_dim"]), int(attrs["output_dim"]))}


register_meta("Embedding", OpMeta(["data", "weight"],
                                  param_shapes=_emb_shapes))


def _lrelu_inputs(attrs):
    if attrs.get("act_type") == "prelu":
        return ["data", "gamma"]
    return ["data"]


def _lrelu_shapes(shapes, attrs):
    if attrs.get("act_type") != "prelu":
        return {}
    data = shapes[0]
    if data is None:
        return {}
    return {1: (data[1],) if len(data) > 1 else (1,)}


register_meta("LeakyReLU", OpMeta(_lrelu_inputs, param_shapes=_lrelu_shapes))


def _rnn_inputs(attrs):
    if attrs.get("mode", "lstm") == "lstm":
        return ["data", "parameters", "state", "state_cell"]
    return ["data", "parameters", "state"]


def _rnn_shapes(shapes, attrs):
    from ..ops.rnn_op import rnn_param_size

    data = shapes[0]
    if data is None:
        return {}
    t, n, input_size = data
    h = int(attrs["state_size"])
    layers = int(attrs["num_layers"])
    bi = bool(attrs.get("bidirectional", False))
    mode = attrs.get("mode", "lstm")
    d = 2 if bi else 1
    out = {1: (rnn_param_size(input_size, h, layers, bi, mode),),
           2: (layers * d, n, h)}
    if mode == "lstm":
        out[3] = (layers * d, n, h)
    return out


register_meta("RNN", OpMeta(_rnn_inputs, param_shapes=_rnn_shapes))

# loss heads: label is a plain input (not auto-shaped)
register_meta("SoftmaxOutput", OpMeta(["data", "label"]))
register_meta("Softmax", OpMeta(["data", "label"]))
register_meta("LinearRegressionOutput", OpMeta(["data", "label"]))
register_meta("MAERegressionOutput", OpMeta(["data", "label"]))
register_meta("LogisticRegressionOutput", OpMeta(["data", "label"]))
register_meta("SVMOutput", OpMeta(["data", "label"]))
