"""Symbol: the declarative graph IR.

TPU-native re-design of the reference's `nnvm::Symbol`/`Graph`
(`3rdparty/tvm/nnvm/include/nnvm/symbolic.h`, `python/mxnet/symbol/
symbol.py:54`).  A Symbol is a small host-side DAG of op nodes; its ONLY
execution path is whole-graph lowering: `simple_bind` turns the entire
graph into a single jitted XLA computation (see `mxtpu.executor`) — the
north-star design where the reference's GraphExecutor ran node-by-node
through the engine.  Consequently the reference's PlanMemory/inplace
passes have no analog (XLA buffer assignment does that); shape/type
inference remains (`infer_shape` solves parameter shapes backward from
the data shape via per-op metadata, then forward via `jax.eval_shape`).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, np_dtype
from ..ops.registry import OpDef, get_op
from . import op_meta as _meta_mod

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "NameManager"]


class NameManager(object):
    """Auto-naming for anonymous ops (reference:
    `python/mxnet/name.py`)."""

    _current = threading.local()

    def __init__(self):
        self._counter: Dict[str, int] = {}

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    @classmethod
    def current(cls) -> "NameManager":
        # a scope exit restores `value = None` when no manager was
        # active before it — treat that the same as never-initialized
        if getattr(cls._current, "value", None) is None:
            cls._current.value = NameManager()
        return cls._current.value

    def __enter__(self):
        # snapshot a REAL manager (creating the thread's default on
        # demand), never None: restoring None on exit would make the
        # next current() call manufacture a fresh manager with reset
        # counters -> duplicate auto-names colliding at bind time
        self._old = NameManager.current()
        NameManager._current.value = self
        return self

    def __exit__(self, *args):
        NameManager._current.value = self._old


class AttrScope(object):
    """with-scope attributes applied to new symbols (reference:
    `python/mxnet/attribute.py`; carries ctx_group etc.)."""

    _current = threading.local()

    def __init__(self, **attrs):
        self._attrs = attrs

    @classmethod
    def current_attrs(cls) -> Dict[str, Any]:
        scope = getattr(cls._current, "value", None)
        return dict(scope._attrs) if scope else {}

    def __enter__(self):
        self._old = getattr(AttrScope._current, "value", None)
        # merge into a transient copy — never mutate self._attrs, the scope
        # object may be reused under different parents
        merged = dict(self._old._attrs) if self._old else {}
        merged.update(self._attrs)
        active = AttrScope()
        active._attrs = merged
        active._old = self._old
        AttrScope._current.value = active
        self._active = active
        return self

    def __exit__(self, *args):
        AttrScope._current.value = self._active._old


class SymbolNode(object):
    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "ext_attrs",
                 "__weakref__")

    def __init__(self, op: Optional[OpDef], name: str, attrs: Dict[str, Any],
                 inputs: List[Tuple["SymbolNode", int]], is_aux: bool = False):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.is_aux = is_aux
        self.ext_attrs: Dict[str, str] = AttrScope.current_attrs()

    @property
    def is_variable(self) -> bool:
        return self.op is None

    def num_outputs(self) -> int:
        if self.op is None:
            return 1
        return self.op.n_outputs(self.attrs)


def _topo_order(out_entries: Sequence[Tuple[SymbolNode, int]]) -> List[SymbolNode]:
    order: List[SymbolNode] = []
    seen = set()
    stack = [(e[0], False) for e in reversed(out_entries)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for (inode, _) in reversed(node.inputs):
            if id(inode) not in seen:
                stack.append((inode, False))
    return order


def _node_attrs(node) -> Dict[str, str]:
    """Stringified user attrs of one node: op attrs then ext_attrs
    (ext wins) — the single merge both list_attr and attr_dict use."""
    d = {k: str(v) for k, v in node.attrs.items()}
    d.update(node.ext_attrs)
    return d


class Symbol(object):
    """Immutable handle to one or more output entries of the graph."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs: Sequence[Tuple[SymbolNode, int]]):
        self._outputs = list(outputs)

    # -- identity ---------------------------------------------------------
    @property
    def name(self) -> str:
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return "grouped"

    def __repr__(self):
        return "<Symbol %s>" % ", ".join(
            "%s[%d]" % (n.name, i) for n, i in self._outputs)

    def __len__(self):
        return len(self.list_outputs())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output %r not found" % index)
            index = names.index(index)
        if isinstance(index, int):
            if index >= len(self.list_outputs()):
                raise MXNetError("output index out of range")
            return Symbol([self._entry_at(index)])
        raise TypeError("bad index %r" % (index,))

    def _entry_at(self, flat_index: int) -> Tuple[SymbolNode, int]:
        i = 0
        for node, idx in self._outputs:
            if i == flat_index:
                return (node, idx)
            i += 1
        raise IndexError(flat_index)

    # -- graph queries ----------------------------------------------------
    def _topo(self) -> List[SymbolNode]:
        return _topo_order(self._outputs)

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_variable and not n.is_aux]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_variable and n.is_aux]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
            elif node.op.n_visible_outputs(node.attrs) == 1:
                names.append(node.name + "_output")
            else:
                names.append("%s_output%d" % (node.name, idx))
        return names

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_variable]

    def get_internals(self) -> "Symbol":
        entries = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        if len(self._outputs) != 1:
            return None
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- attrs ------------------------------------------------------------
    def attr(self, key: str) -> Optional[str]:
        node = self._outputs[0][0]
        v = node.ext_attrs.get(key)
        if v is None and key in node.attrs:
            v = str(node.attrs[key])
        return v

    def _set_attr(self, **kwargs):
        node = self._outputs[0][0]
        node.ext_attrs.update({k: str(v) for k, v in kwargs.items()})

    def list_attr(self, recursive: bool = False) -> Dict[str, str]:
        """Attributes of the HEAD node only (reference
        `Symbol.list_attr`; `recursive=True` was deprecated there in
        favor of `attr_dict`)."""
        if recursive:
            raise MXNetError(
                "list_attr(recursive=True) is removed — use attr_dict()"
                " (reference deprecation, symbol.py)")
        return _node_attrs(self._outputs[0][0])

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for node in self._topo():
            d = _node_attrs(node)
            if d:
                out[node.name] = d
        return out

    # -- shape/type inference --------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, Tuple[int, ...]] = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        shapes, dtypes = _infer_graph(self, known, {}, partial=partial)
        arg_shapes = [shapes.get(n) for n in arg_names]
        out_shapes = [shapes.get(node.name) if node.is_variable
                      else shapes.get(("out", id(node), idx))
                      for node, idx in self._outputs]
        aux_shapes = [shapes.get(n) for n in self.list_auxiliary_states()]
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError("infer_shape incomplete; unknown args: %s"
                             % missing)
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, Any] = {}
        if args:
            for name, dt in zip(arg_names, args):
                if dt is not None:
                    known[name] = np_dtype(dt)
        known.update({k: np_dtype(v) for k, v in kwargs.items()
                      if v is not None})
        # honor declared variable dtypes
        decl = {}
        for n in self._topo():
            if n.is_variable and "__dtype__" in n.ext_attrs:
                decl[n.name] = np.dtype(n.ext_attrs["__dtype__"])
        arg_types = [known.get(n, decl.get(n, np.dtype(np.float32)))
                     for n in arg_names]
        # propagate through the graph when shapes are declared/known;
        # otherwise fall back to float32 per output
        out_types = [np.dtype(np.float32)] * len(self.list_outputs())
        try:
            shapes, dtypes = _infer_graph(self, {}, dict(known), partial=True)
            out_types = [
                dtypes.get(node.name, np.dtype(np.float32)) if node.is_variable
                else (dtypes.get(("out", id(node), idx)) or
                      np.dtype(np.float32))
                for node, idx in self._outputs
            ]
        except Exception:
            pass
        aux_types = [np.dtype(np.float32)] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # -- composition ------------------------------------------------------
    def __call__(self, *args, **kwargs) -> "Symbol":
        """Compose: substitute this symbol's variable inputs with other
        symbols (reference `Symbol.__call__`/Compose)."""
        mapping: Dict[str, Symbol] = {}
        arg_names = [n for n in self.list_inputs()]
        if args:
            for name, s in zip(arg_names, args):
                mapping[name] = s
        mapping.update(kwargs)
        if not mapping:
            return self
        for s in mapping.values():
            if len(s._outputs) != 1:
                raise MXNetError("can only compose with 1-output symbols")
        memo: Dict[int, Tuple[SymbolNode, int]] = {}

        def clone_entry(entry) -> Tuple[SymbolNode, int]:
            """Clone an (node, out_idx) entry, substituting variables with
            the mapped symbol's full entry (node AND output index)."""
            node, idx = entry
            if id(node) in memo:
                n, sub_idx = memo[id(node)]
                # substituted variables carry their own output index;
                # ordinary nodes keep the consumer's index
                return (n, sub_idx if sub_idx is not None else idx)
            if node.is_variable and node.name in mapping:
                sub_entry = mapping[node.name]._outputs[0]
                memo[id(node)] = (sub_entry[0], sub_entry[1])
                return sub_entry
            new = SymbolNode(node.op, node.name, dict(node.attrs),
                             [clone_entry(e) for e in node.inputs],
                             is_aux=node.is_aux)
            new.ext_attrs = dict(node.ext_attrs)
            memo[id(node)] = (new, None)
            return (new, idx)

        return Symbol([clone_entry(e) for e in self._outputs])

    # -- arithmetic -------------------------------------------------------
    def _binary(self, other, op_name, scalar_op, rscalar_op=None, swap=False):
        from .register import invoke_symbol

        if isinstance(other, Symbol):
            a, b = (other, self) if swap else (self, other)
            return invoke_symbol(op_name, [a, b], {})
        if isinstance(other, (int, float, np.generic)):
            name = rscalar_op if (swap and rscalar_op) else scalar_op
            return invoke_symbol(name, [self], {"scalar": float(other)})
        raise TypeError(type(other))

    def __add__(self, other):
        return self._binary(other, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "elemwise_sub", "_minus_scalar",
                            "_rminus_scalar", swap=True)

    def __mul__(self, other):
        return self._binary(other, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binary(other, "elemwise_div", "_div_scalar",
                            "_rdiv_scalar", swap=True)

    def __pow__(self, other):
        return self._binary(other, "_power", "_power_scalar")

    def __neg__(self):
        from .register import invoke_symbol

        return invoke_symbol("negative", [self], {})

    def __eq__(self, other):
        if isinstance(other, (Symbol, int, float, np.generic)):
            return self._binary(other, "_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Symbol, int, float, np.generic)):
            return self._binary(other, "_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return self._binary(other, "_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    # -- serialization ----------------------------------------------------
    def tojson(self) -> str:
        nodes = self._topo()
        node_index = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: json.dumps(_jsonable(v))
                          for k, v in n.attrs.items()},
                "ext_attrs": dict(n.ext_attrs),
                "inputs": [[node_index[id(i)], idx, 0] for i, idx in n.inputs],
                "is_aux": n.is_aux,
            })
        heads = [[node_index[id(n)], idx, 0] for n, idx in self._outputs]
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "heads": heads,
                           "attrs": {"mxtpu_version": ["str", "0.1.0"]}},
                          indent=2)

    def save(self, fname: str):
        from ..resilience import atomic_write

        with atomic_write(fname, "w") as f:
            f.write(self.tojson())

    # -- binding (whole-graph XLA lowering) -------------------------------
    @staticmethod
    def _check_group2ctx(group2ctx, ctx):
        """Reference `graph_executor.cc:1594` places ctx-grouped subgraphs
        on distinct devices.  The TPU-native counterpart of that kind of
        model parallelism is mesh sharding (`mxtpu.parallel`), not
        per-node device placement — so a group2ctx that actually asks
        for multi-device placement raises instead of being silently
        ignored.  A mapping where every group lands on the bind context
        is a no-op and accepted."""
        if not group2ctx:
            return
        from ..context import current_context

        distinct = {str(c) for c in group2ctx.values()}
        distinct.add(str(ctx if ctx is not None else current_context()))
        if len(distinct) > 1:
            raise NotImplementedError(
                "group2ctx with multi-device placement (%s) is not "
                "supported: whole-graph XLA lowering places the graph on "
                "one logical device. Use mesh-based model parallelism "
                "(mxtpu.parallel: pjit shardings over a Mesh) instead."
                % sorted(distinct))

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor
        from ..subgraph import apply_bind_hook

        Symbol._check_group2ctx(group2ctx, ctx)
        return Executor._simple_bind(apply_bind_hook(self), ctx, grad_req,
                                     type_dict, kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        from ..subgraph import apply_bind_hook

        Symbol._check_group2ctx(group2ctx, ctx)
        return Executor._bind(apply_bind_hook(self), ctx, args, args_grad,
                              grad_req, aux_states)

    def optimize(self, passes=None, return_report: bool = False):
        """Run the `mxtpu.passes` graph-rewrite pipeline over this
        symbol and return the optimized Symbol (the original graph is
        untouched).  ``passes`` is a spec like ``"dce,fold"`` /
        ``"default,-fuse"`` / a name sequence; None uses the active
        ``MXTPU_PASSES`` configuration.  ``return_report=True`` returns
        ``(symbol, report)`` with per-pass node counts and stats —
        that report is what ``tools/hlo_report.py --symbol-json``
        prints as pre/post deltas.

        Note: a graph holding folded constants binds and analyzes
        normally but does not round-trip through ``tojson``/``load``
        (the constant op carries its value in a closure)."""
        from .. import passes as _passes

        opt, report = _passes.optimize(self, passes)
        return (opt, report) if return_report else opt

    def optimize_for(self, backend, args=None, aux=None, **kwargs):
        """Apply a registered subgraph backend to this graph (the
        reference's `Symbol.optimize_for` / `MXNET_SUBGRAPH_BACKEND`
        partitioning, `src/operator/subgraph/partition_graph.cc`).

        Parameter-free backends return the partitioned Symbol; backends
        that rewrite parameter values (e.g. ``"TPU"`` Conv+BN folding)
        require `args`/`aux` dicts and return
        ``(symbol, new_args, new_aux)``."""
        from ..subgraph import partition

        if kwargs:
            raise TypeError(
                "optimize_for: unsupported backend options %s (this "
                "build's backends take their configuration at "
                "registration time)" % sorted(kwargs))
        return partition(self, backend, arg_params=args, aux_params=aux)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx=ctx, args=kwargs)
        return ex.forward()

    # convenience mirrors of common ops
    def _invoke(self, op, attrs=None):
        from .register import invoke_symbol

        return invoke_symbol(op, [self], attrs or {})

    def reshape(self, shape, **kw):
        return self._invoke("Reshape", {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return self._invoke("transpose", {"axes": tuple(axes) if axes else None})

    def sum(self, axis=None, keepdims=False):
        return self._invoke("sum", {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return self._invoke("mean", {"axis": axis, "keepdims": keepdims})

    def softmax(self, axis=-1):
        return self._invoke("softmax", {"axis": axis})

    def flatten(self):
        return self._invoke("Flatten", {})

    def slice_axis(self, axis, begin, end):
        return self._invoke("slice_axis",
                            {"axis": axis, "begin": begin, "end": end})

    def expand_dims(self, axis):
        return self._invoke("expand_dims", {"axis": axis})

    def squeeze(self, axis=None):
        return self._invoke("squeeze", {"axis": axis})

    def astype(self, dtype):
        return self._invoke("Cast", {"dtype": np_dtype(dtype).name})


def _jsonable(v):
    if isinstance(v, np.dtype):
        return v.name
    if isinstance(v, tuple):
        return list(v)
    return v


def _unjson(v):
    if isinstance(v, list):
        return tuple(v)
    return v


def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs) -> Symbol:
    """Create a variable symbol (reference `mx.sym.Variable`): `attr`
    entries and the lr_mult/wd_mult/init conveniences persist as node
    attributes (reference spells them __lr_mult__ etc. in attr_dict)."""
    node = SymbolNode(None, name, {}, [])
    if shape is not None:
        node.ext_attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        node.ext_attrs["__dtype__"] = np_dtype(dtype).name
    if attr:
        node.ext_attrs.update({k: str(v) for k, v in attr.items()})
    if lr_mult is not None:
        node.ext_attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        node.ext_attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        # reference stores init.dumps() (a JSON hint the initializer
        # consumer parses); plain strings pass through as names
        node.ext_attrs["__init__"] = (init.dumps()
                                      if hasattr(init, "dumps")
                                      else str(init))
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._outputs)
    return Symbol(entries)


def load_json(json_str: str) -> Symbol:
    data = json.loads(json_str)
    nodes: List[SymbolNode] = []
    for jn in data["nodes"]:
        attrs = {k: _unjson(json.loads(v)) for k, v in jn.get("attrs", {}).items()}
        if jn["op"] == "null":
            node = SymbolNode(None, jn["name"], {}, [],
                              is_aux=jn.get("is_aux", False))
        else:
            op = get_op(jn["op"])
            inputs = [(nodes[i], idx) for i, idx, _ in jn["inputs"]]
            node = SymbolNode(op, jn["name"], attrs, inputs)
        node.ext_attrs = dict(jn.get("ext_attrs", {}))
        nodes.append(node)
    outputs = [(nodes[i], idx) for i, idx, _ in data["heads"]]
    return Symbol(outputs)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# Whole-graph shape inference (reference: infer_graph_attr_pass.cc, but
# forward shapes come from jax.eval_shape and parameter shapes from the
# op_meta backward hooks)
# ---------------------------------------------------------------------------

def _infer_graph(symbol: Symbol, known_shapes: Dict[str, Tuple[int, ...]],
                 known_dtypes: Dict[str, Any], partial: bool = False):
    import jax

    shapes: Dict[Any, Optional[Tuple[int, ...]]] = {}
    dtypes: Dict[Any, Any] = {}
    nodes = symbol._topo()

    def var_shape(node):
        if node.name in known_shapes:
            return tuple(known_shapes[node.name])
        if "__shape__" in node.ext_attrs:
            import ast

            return tuple(ast.literal_eval(node.ext_attrs["__shape__"]))
        return None

    def var_dtype(node):
        if node.name in known_dtypes:
            return np.dtype(known_dtypes[node.name])
        if "__dtype__" in node.ext_attrs:
            return np.dtype(node.ext_attrs["__dtype__"])
        return np.dtype(np.float32)

    for node in nodes:
        if node.is_variable:
            shapes[node.name] = var_shape(node)
            dtypes[node.name] = var_dtype(node)
            continue
        meta = _meta_mod.get_meta(node.op)
        in_entries = node.inputs
        in_shapes = []
        for (inode, idx) in in_entries:
            if inode.is_variable:
                in_shapes.append(shapes.get(inode.name))
            else:
                in_shapes.append(shapes.get(("out", id(inode), idx)))
        # backward-solve unknown parameter shapes from the data shape
        if meta.param_shapes is not None and any(s is None for s in in_shapes):
            solved = meta.param_shapes(in_shapes, node.attrs)
            for i, shp in (solved or {}).items():
                if i < len(in_entries) and in_shapes[i] is None:
                    inode, _ = in_entries[i]
                    if inode.is_variable and shapes.get(inode.name) is None:
                        shapes[inode.name] = tuple(shp)
                        in_shapes[i] = tuple(shp)
        if any(s is None for s in in_shapes):
            if partial:
                for i in range(node.num_outputs()):
                    shapes[("out", id(node), i)] = None
                continue
            missing = [in_entries[i][0].name for i, s in enumerate(in_shapes)
                       if s is None]
            raise MXNetError("cannot infer shape for inputs %s of node %s"
                             % (missing, node.name))
        in_dtypes = []
        for (inode, idx), shp in zip(in_entries, in_shapes):
            if inode.is_variable:
                in_dtypes.append(dtypes.get(inode.name, np.dtype(np.float32)))
            else:
                in_dtypes.append(dtypes.get(("out", id(inode), idx),
                                            np.dtype(np.float32)))
        out_shapes, out_dtypes = _eval_node_shape(node, in_shapes, in_dtypes)
        for i, (shp, dt) in enumerate(zip(out_shapes, out_dtypes)):
            shapes[("out", id(node), i)] = shp
            dtypes[("out", id(node), i)] = dt
    return shapes, dtypes


def _eval_node_shape(node: SymbolNode, in_shapes, in_dtypes):
    import functools

    import jax

    op = node.op
    attrs = dict(node.attrs)
    if op.train_aware:
        attrs.setdefault("is_train", False)

    structs = [jax.ShapeDtypeStruct(s, d) for s, d in zip(in_shapes, in_dtypes)]
    fn = functools.partial(op.fn, **attrs)
    if op.needs_rng:
        key = jax.ShapeDtypeStruct((2,), np.uint32)
        out = jax.eval_shape(fn, key, *structs)
    else:
        out = jax.eval_shape(fn, *structs)
    if not isinstance(out, tuple):
        out = (out,)
    return [tuple(o.shape) for o in out], [np.dtype(o.dtype) for o in out]
