"""Weight initializers (reference: `python/mxnet/initializer.py`).

Same registry/factory surface (`mx.init.Xavier()`, string shortcuts,
pattern-based Mixed); initialization itself draws from the framework RNG
chain so `mx.random.seed` reproduces parameter init like the reference.
"""
from __future__ import annotations

import json
import math
import re
from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Zero", "One",
           "Constant", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Mixed", "Load", "register", "create"]

_INIT_REGISTRY: Dict[str, type] = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs) -> "Initializer":
    if isinstance(name, Initializer):
        return name
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _INIT_REGISTRY[key](**kwargs)


class InitDesc(str):
    """Parameter name + attrs hint (reference `initializer.py:46`)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer(object):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    # -- dispatch by parameter name, like the reference ------------------
    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init_hint = desc.attrs.get("__init__", "")
        if init_hint:
            if init_hint.startswith("["):
                # dumps() format: ["name", {kwargs}] — the kwargs carry
                # the configured state (e.g. Constant's value)
                hint_name, hint_kwargs = json.loads(init_hint)
                init = create(hint_name, **(hint_kwargs or {}))
            else:
                init = create(init_hint)
            init._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def init_weight(self, desc, arr):
        self._init_weight(desc, arr)

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _set(arr, value: np.ndarray):
        from .ndarray.ndarray import NDArray

        if isinstance(arr, NDArray):
            arr._set_jax(__import__("jax").device_put(
                value.astype(np.dtype(arr.dtype)), arr._data.device))
        else:
            arr[:] = value

    def _rand_uniform(self, shape, low, high):
        from . import random as _rnd

        return np.asarray(_rnd.uniform(low, high, shape=tuple(shape)).asnumpy())

    def _rand_normal(self, shape, sigma):
        from . import random as _rnd

        return np.asarray(_rnd.normal(0.0, sigma, shape=tuple(shape)).asnumpy())

    def _init_zero(self, desc, arr):
        self._set(arr, np.zeros(arr.shape, dtype=np.float32))

    def _init_one(self, desc, arr):
        self._set(arr, np.ones(arr.shape, dtype=np.float32))

    def _init_bias(self, desc, arr):
        self._init_zero(desc, arr)

    def _init_gamma(self, desc, arr):
        self._init_one(desc, arr)

    def _init_beta(self, desc, arr):
        self._init_zero(desc, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __repr__(self):
        return "%s(%s)" % (self.__class__.__name__, self._kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        self._init_zero(desc, arr)


_INIT_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        self._init_one(desc, arr)


_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        self._set(arr, np.full(arr.shape, self.value, dtype=np.float32))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        self._set(arr, self._rand_uniform(arr.shape, -self.scale, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        self._set(arr, self._rand_normal(arr.shape, self.sigma))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = self._rand_uniform((nout, nin), -1.0, 1.0)
        else:
            tmp = self._rand_normal((nout, nin), 1.0)
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * q).reshape(arr.shape))


@register
class Xavier(Initializer):
    """Reference `initializer.py` Xavier: magnitude scaled by fan in/out."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(
                "Xavier initializer needs >= 2D weight, got %s for %s"
                % (shape, desc))
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("bad factor_type %r" % self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, self._rand_uniform(shape, -scale, scale))
        else:
            self._set(arr, self._rand_normal(shape, scale))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for Deconvolution upsampling layers)."""

    def _init_weight(self, desc, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference `initializer.py` LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        n = arr.shape[0] // 4
        b[n:2 * n] = self.forget_bias  # i, f, g, o gate order
        self._set(arr, b)

    _init_default = _init_weight
    _init_bias = _init_weight


class Load(object):
    """Init from saved dict, fall back to default (reference Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        from .ndarray import load as nd_load

        if isinstance(param, str):
            param = nd_load(param)
        self.param = {k.replace("arg:", "").replace("aux:", ""): v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError("shape mismatch loading %r" % name)
            Initializer._set(arr, src.asnumpy())
        else:
            if self.default_init is None:
                raise MXNetError("no init for %r" % name)
            self.default_init(name, arr)


class Mixed(object):
    """Pattern-matched initializer list (reference Mixed)."""

    def __init__(self, patterns: List[str], initializers: List[Initializer]):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns/initializers length mismatch")
        self.map = [(re.compile(p), init)
                    for p, init in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.search(str(name)):
                init(name, arr)
                return
        raise MXNetError("no initializer matched %r; add a '.*' pattern"
                         % str(name))


class init(object):  # namespace alias: mx.init.Xavier()
    InitDesc = InitDesc
    Initializer = Initializer
    Uniform = Uniform
    Normal = Normal
    Zero = Zero
    One = One
    Constant = Constant
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    Mixed = Mixed
    Load = Load
