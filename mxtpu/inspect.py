"""Program inspector: compiled-program registry, retrace blame, traces.

The compile-time mirror of `mxtpu/telemetry.py` (which watches the
*runtime*): every XLA program this framework builds — Executor
`_jit_*` dispatch, CachedOp, FusedTrainLoop, and `compile_cache.
aot_compile` warmups — registers here, so "retraces: 7" in
`profiler.stats()` becomes an actionable diagnosis.  Three pieces:

  * **Compiled-program registry** — one :class:`ProgramRecord` per
    logical program (keyed ``site:symbol-name``) holding every input
    signature it compiled, the compile wall time per signature, the
    cache-hit count, and — lazily, on first request — XLA's own
    ``cost_analysis()`` (FLOPs, bytes accessed) and
    ``memory_analysis()`` (argument/output/temp/peak bytes) plus the
    optimized HLO text.  Surfaced as :func:`programs` /
    :func:`summary` / :func:`report` / :func:`hlo`.

  * **Retrace blame** — when a program compiles a SECOND (third, ...)
    signature, the new signature is diffed against the cached ones and
    a human-readable culprit is produced ("arg `data0` shape
    (32, 3, 224, 224)→(33, 3, 224, 224): ... enable shape buckets").
    The culprit rides on the telemetry ``compile`` event (``blame``
    field), ticks a per-culprit ``retrace_blame::...`` counter in
    ``profiler.stats()``, and aggregates in :func:`blame_summary`.

  * **Layer attribution** — `executor._build_graph_fn` wraps every
    symbol-node invocation in ``jax.named_scope(node.name)`` (opt out:
    ``MXTPU_INSPECT_SCOPES=0``), so HLO op metadata (``op_name=...``
    in :func:`hlo` output) and `jax.profiler` device traces resolve to
    model layers.  :func:`trace` is the supported device-trace entry
    point (wraps ``jax.profiler.start_trace``/``stop_trace``).

Cost discipline: the cache-HIT path is one enabled-check plus one
unlocked integer bump (<10 us measured by ``tools/check_inspect.py``
--overhead; see `docs/observability.md`).  Cost/memory analysis needs
its own ``jit.lower().compile()`` (JAX exposes no handle to the
executable the dispatch cache built), so it runs LAZILY at inspect
time — never on the training path — and is cached per signature; with
the persistent compile cache armed the XLA part is a disk hit.
``MXTPU_INSPECT_EAGER=1`` moves the analysis to compile time (each new
program then pays one extra trace+compile) so telemetry ``compile``
events ship real ``flops``/``peak_bytes`` immediately; otherwise those
fields start at 0 and are backfilled in place once analysis runs.
``MXTPU_INSPECT=0`` opts out of all registry bookkeeping (the plain
telemetry ``compile`` records keep flowing).
"""
from __future__ import annotations

import collections
import contextlib
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError, getenv, getenv_bool

__all__ = [
    "enabled",
    "enable",
    "scopes_enabled",
    "program",
    "programs",
    "summary",
    "find",
    "find_for_symbol",
    "hlo",
    "report",
    "hlo_histogram",
    "op_flops",
    "trace",
    "EmptyTraceError",
    "blame_summary",
    "analyze_all",
    "reset",
]

_ENABLED = getenv_bool("MXTPU_INSPECT", True)
_EAGER = getenv_bool("MXTPU_INSPECT_EAGER", False)
# bound both axes of registry growth: a long-lived process (or the
# test suite) creates thousands of executors, and each record pins its
# jit fn (and through it the compiled executable) for lazy analysis
_MAX_PROGRAMS = max(8, int(getenv("MXTPU_INSPECT_MAX", "512") or 512))
_MAX_SIGS = max(2, int(getenv("MXTPU_INSPECT_SIGS", "32") or 32))

_lock = threading.RLock()
# guards every compile site's seen-signature set on the dispatch hot
# path (track_compile): serving threads sharing one CachedOp must
# resolve a brand-new signature to exactly ONE compile token
_sig_lock = threading.Lock()
# serializes the global compile-cache config flip in _compile_uncached
# (never held together with _lock; analysis runs outside _lock)
_cfg_lock = threading.Lock()
_REGISTRY: "collections.OrderedDict[str, ProgramRecord]" = \
    collections.OrderedDict()
_BLAME: "collections.Counter" = collections.Counter()


def enabled() -> bool:
    """Registry bookkeeping on?  ``MXTPU_INSPECT=0`` opts out."""
    return _ENABLED


def enable(on: bool = True) -> None:
    """Flip the inspector at runtime (tests / embedding)."""
    global _ENABLED
    _ENABLED = bool(on)


def scopes_enabled() -> bool:
    """Layer-attribution ``jax.named_scope`` wrapping in the graph
    builder (``MXTPU_INSPECT_SCOPES``, default on).  Read at graph
    BUILD time — flipping it after bind needs a rebind."""
    return _ENABLED and getenv_bool("MXTPU_INSPECT_SCOPES", True)


_SCOPE_RE = re.compile(r"[^\w.\-/]")


def scope_name(name: str) -> str:
    """A symbol-node name sanitized for ``jax.named_scope`` (the HLO
    metadata pipeline treats ``/`` as a scope separator)."""
    return _SCOPE_RE.sub("_", name) or "op"


# ---------------------------------------------------------------------------
# Signature helpers
# ---------------------------------------------------------------------------

def _sig_of_tree(example_args) -> Tuple:
    """Hashable (shape, dtype) signature over an arbitrary pytree of
    arrays / ShapeDtypeStructs (the aot_compile entry point)."""
    import jax

    leaves = jax.tree_util.tree_leaves(example_args)
    # dtype OBJECTS, matching compile_cache.sig_of
    return tuple((tuple(v.shape), v.dtype) for v in leaves
                 if hasattr(v, "shape") and hasattr(v, "dtype"))


def _to_structs(example_args):
    """Pytree of arrays -> ShapeDtypeStructs (metadata only — works on
    donated/deleted buffers too, whose avals survive the delete)."""
    import jax

    def leaf(v):
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
        return v

    return jax.tree_util.tree_map(leaf, example_args)


# ---------------------------------------------------------------------------
# Retrace blame
# ---------------------------------------------------------------------------

_BUCKET_HINT = ("enable shape buckets (MXTPU_SHAPE_BUCKETS=pow2 or "
                "hybridize(shape_buckets=...))")


def _arg_label(arg_names: Optional[Sequence[str]], i: int) -> str:
    if arg_names and i < len(arg_names):
        return arg_names[i]
    return "arg%d" % i


def _diff_sigs(arg_names, old_sig, new_sig) -> List[Tuple[str, str, str]]:
    """Per-argument diffs between two equal-length signatures:
    (arg name, field, human description)."""
    diffs = []
    for i, (o, n) in enumerate(zip(old_sig, new_sig)):
        if o == n:
            continue
        name = _arg_label(arg_names, i)
        (os_, od), (ns, nd) = o, n
        if os_ != ns:
            if len(os_) == len(ns) and os_[1:] == ns[1:]:
                hint = "leading (batch) dim churn — " + _BUCKET_HINT
            else:
                hint = ("pad or fix this dimension host-side (every "
                        "distinct shape compiles a new program)")
            diffs.append((name, "shape", "arg `%s` shape %s→%s: %s"
                          % (name, os_, ns, hint)))
        if od != nd:
            diffs.append((name, "dtype",
                          "arg `%s` dtype %s→%s: cast once at the input "
                          "boundary (the graph retraced for the new dtype)"
                          % (name, od, nd)))
    return diffs


def compute_blame(arg_names, prior_sigs: Sequence[Tuple],
                  new_sig: Tuple) -> Tuple[Optional[str], List[Tuple]]:
    """Diff ``new_sig`` against the cached signatures of the same
    program/kind and name the culprit.  Returns (human blame string or
    None, [(arg, field), ...] culprit keys)."""
    if not prior_sigs:
        return None, []
    same_len = [s for s in prior_sigs if len(s) == len(new_sig)]
    if not same_len:
        closest = prior_sigs[-1]
        msg = ("arg count %d→%d (graph inputs changed): input-structure "
               "churn retraces the whole program"
               % (len(closest), len(new_sig)))
        return msg, [("*", "arity")]
    best = min(same_len,
               key=lambda s: sum(a != b for a, b in zip(s, new_sig)))
    diffs = _diff_sigs(arg_names, best, new_sig)
    if not diffs:  # identical sig resubmitted as new (shouldn't happen)
        return None, []
    shown = [d[2] for d in diffs[:3]]
    if len(diffs) > 3:
        shown.append("(+%d more args changed)" % (len(diffs) - 3))
    return "; ".join(shown), [(d[0], d[1]) for d in diffs]


# ---------------------------------------------------------------------------
# Registry records
# ---------------------------------------------------------------------------

def _compile_uncached(lowered):
    """Diagnostic (inspect-time) compiles bypass the persistent
    compile cache: its key canonicalizes out op_name metadata, so an
    EQUIVALENT program compiled under different layer names in another
    run sharing the cache dir can satisfy the lookup — and
    ``hlo_text()`` would then show the twin's layer names, defeating
    attribution.  Cost/memory figures are name-independent, but the
    text must come from THIS program's lowering."""
    import jax

    from . import compile_cache as _cc

    # The flip is process-global, so two concurrent diagnostic
    # compiles must not interleave their save/restore (the second
    # would snapshot None and "restore" the cache to disabled).
    with _cfg_lock:
        try:
            # jax_enable_compilation_cache alone is a no-op on 0.4.x
            # once the per-process cache decision has latched; clearing
            # the dir and resetting the latch is the lever that works.
            prev = jax.config.jax_compilation_cache_dir
            jax.config.update("jax_compilation_cache_dir", None)
            _cc._reset_jax_cache_latch()
        except Exception:
            return lowered.compile()
        try:
            return lowered.compile()
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            _cc._reset_jax_cache_latch()


class _SigInfo(object):
    """One compiled signature of one program: compile wall time, the
    blame that triggered it, and the lazy analysis handle."""

    __slots__ = ("kind", "sig", "blame", "compile_wall_s", "aot", "ts",
                 "event", "_jitfn", "_structs", "_compiled", "_analysis",
                 "_hlo")

    def __init__(self, kind: str, sig: Tuple, blame: Optional[str],
                 event: Optional[dict]):
        self.kind = kind
        self.sig = sig
        self.blame = blame
        self.compile_wall_s = 0.0
        self.aot = False
        self.ts = time.time()
        self.event = event  # telemetry compile record (backfilled)
        self._jitfn = None
        self._structs = None
        self._compiled = None
        self._analysis = None
        self._hlo = None

    def set_lowerable(self, jitfn, example_args) -> None:
        try:
            self._structs = _to_structs(example_args)
            self._jitfn = jitfn
        except Exception:
            self._jitfn = self._structs = None

    def analyze(self) -> Dict[str, Any]:
        """XLA cost + memory analysis for this signature (cached).
        Needs its own ``lower().compile()`` when the record was not
        AOT-built — run at inspect time, never on the hot path."""
        if self._analysis is not None:
            return self._analysis
        out: Dict[str, Any] = {}
        try:
            compiled = self._compiled
            if compiled is None:
                if self._jitfn is None:
                    raise MXNetError("no lowerable handle recorded")
                lowered = self._jitfn.lower(*self._structs)
                compiled = _compile_uncached(lowered)
                self._compiled = compiled
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            out["flops"] = float(ca.get("flops", 0.0) or 0.0)
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0)
                                          or 0.0)
            out["transcendentals"] = float(ca.get("transcendentals", 0.0)
                                           or 0.0)
            ma = compiled.memory_analysis()
            arg = int(ma.argument_size_in_bytes)
            outb = int(ma.output_size_in_bytes)
            tmp = int(ma.temp_size_in_bytes)
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
            out["argument_bytes"] = arg
            out["output_bytes"] = outb
            out["temp_bytes"] = tmp
            out["alias_bytes"] = alias
            # donated outputs alias argument buffers — don't double-count
            out["peak_bytes"] = arg + tmp + max(0, outb - alias)
        except Exception as e:  # analysis is best-effort diagnostics
            out.setdefault("flops", 0.0)
            out.setdefault("peak_bytes", 0)
            out["error"] = str(e)[:300]
        self._analysis = out
        ev = self.event
        if ev is not None:
            # the ring holds this dict by reference: filling the
            # pre-created keys in place (no size change) retroactively
            # enriches flight/telemetry dumps written later
            ev["flops"] = out.get("flops", 0.0)
            ev["peak_bytes"] = out.get("peak_bytes", 0)
        return out

    def hlo_text(self) -> str:
        """Optimized HLO text of this signature (compiles lazily)."""
        if self._hlo is None:
            self.analyze()
            if self._compiled is None:
                raise MXNetError("HLO unavailable: %s"
                                 % self._analysis.get("error", "no handle"))
            self._hlo = self._compiled.as_text()
        return self._hlo

    def as_dict(self) -> Dict[str, Any]:
        d = {"kind": self.kind, "signature": self.sig,
             "compile_wall_s": round(self.compile_wall_s, 6),
             "aot": self.aot, "ts": self.ts}
        if self.blame:
            d["blame"] = self.blame
        if self._analysis is not None:
            d.update(self._analysis)
        return d


class _Pending(object):
    """Token bridging ``begin_compile`` (before the jit dispatch) to
    the point right after it, where wall time and the lowerable handle
    become known."""

    __slots__ = ("prog", "si", "t0")

    def __init__(self, prog: "ProgramRecord", si: _SigInfo):
        self.prog = prog
        self.si = si
        self.t0 = time.perf_counter()

    def done(self, jitfn=None, example_args=None) -> None:
        from . import profiler as _prof

        wall = time.perf_counter() - self.t0
        si = self.si
        si.compile_wall_s = wall
        self.prog.compile_wall_s += wall
        _prof.inc_stat("inspect_compile_wall_us", int(wall * 1e6))
        if si.event is not None:
            si.event["compile_s"] = round(wall, 6)
        if jitfn is not None and example_args is not None:
            si.set_lowerable(jitfn, example_args)
        if _EAGER:
            si.analyze()


class ProgramRecord(object):
    """One logical compiled program (all its signatures)."""

    def __init__(self, site: str, name: str):
        self.site = site
        self.name = name
        self.created = time.time()
        self.arg_names: Optional[List[str]] = None
        # graph-rewrite provenance (mxtpu.passes report) of the symbol
        # this program lowered — set by program() when the pass
        # pipeline optimized the graph, so "this fusion created this
        # HLO region" is answerable from the registry
        self.pass_report: Optional[Dict[str, Any]] = None
        # sharding provenance (mx.shard): the plan the shard pass
        # stamped on this program's graph (or the plan active at
        # registration), e.g. "zero1:n=4,axis=dp" — rides every
        # telemetry ``compile`` event as the ``sharding`` field
        self.sharding: Optional[str] = None
        # tuning provenance (mx.tune): the auto-applied tuning-DB
        # config this program was built under, e.g.
        # "tune:key=ab12cd34,donate=0,passes=default" — set by
        # program() when `MXTPU_TUNE=apply` resolved a DB entry
        self.tuning: Optional[str] = None
        # latest measured per-op attribution (mx.xprof, compact form:
        # totals + per-class rollup + top sinks) — set by
        # xprof.attach() whenever this program is profiled
        self.op_profile: Optional[Dict[str, Any]] = None
        # device-memory layout hints (mx.hbm): how this site's flat
        # example-arg tree maps onto param/aux/data/opt-state slots —
        # set by the dispatch sites at registration, consumed by
        # hbm.plan()'s input-leaf classifier
        self.mem_layout: Optional[Dict[str, Any]] = None
        # latest decoded per-class/per-layer memory plan (mx.hbm.plan
        # attaches it; rides report() as "memory_plan")
        self.memory_plan: Optional[Dict[str, Any]] = None
        self.hits = 0          # unlocked bump: the <10us hot path
        self.compiles = 0      # dispatch-path compiles (ticks *_trace)
        self.aot_compiles = 0  # warmup/AOT builds (ticks *_warmup)
        self.compile_wall_s = 0.0
        self.sigs: "collections.OrderedDict[Tuple[str, Tuple], _SigInfo]" \
            = collections.OrderedDict()
        self._sym_head = None  # weakref to the symbol's head node

    # -- hot path ---------------------------------------------------------
    def hit(self) -> None:
        if _ENABLED:
            # under _sig_lock: a bare += from N serving threads loses
            # increments, and check_inspect RECONCILES these totals
            # against the (locked) profiler counters
            with _sig_lock:
                self.hits += 1

    # -- compile path -----------------------------------------------------
    def begin_compile(self, kind: str, sig: Tuple,
                      arg_names: Optional[Sequence[str]] = None,
                      site: Optional[str] = None) -> Optional[_Pending]:
        """Register a NEW signature about to compile.  Emits the
        telemetry ``compile`` event (with blame when this is a
        retrace), ticks the blame counters, and returns a token whose
        ``done()`` the call site invokes right after the jit dispatch.
        Returns None (after emitting the plain event) when the
        inspector is disabled."""
        from . import profiler as _prof
        from . import telemetry as _tel

        site = site or self.site
        blame = None
        if _ENABLED:
            names = list(arg_names) if arg_names is not None \
                else self.arg_names
            with _lock:
                # AOT sigs span the site's FULL example-arg tree (aux,
                # rng key, ...) while dispatch sigs cover only the
                # tracked args — different domains, so diffing across
                # them would fabricate arity blame
                prior = [s.sig for (k, _), s in self.sigs.items()
                         if k == kind and not s.aot]
                blame, culprits = compute_blame(names, prior, sig)
            if blame:
                _BLAME[blame] += 1
                _prof.inc_stat("inspect_recompiles")
                for arg, field in culprits:
                    _prof.inc_stat("retrace_blame::%s:%s:%s"
                                   % (self.name, arg, field))
        # flops/peak_bytes/compile_s are pre-created at 0 and later
        # BACKFILLED by assignment only: the dict is already in the
        # telemetry ring, and growing it there would race concurrent
        # heartbeat/flight serialization (dict-changed-size errors).
        # `passes` (graph-rewrite provenance, e.g. "dce,cse,fuse:34->21")
        # is complete at record time — never backfilled.
        pass_prov = None
        if self.pass_report is not None:
            from . import passes as _passes

            pass_prov = _passes.provenance_summary(self.pass_report)
        ev = _tel.record("compile", site=site, step=_tel.current_step(),
                         program=self.name, variant=kind, flops=0.0,
                         peak_bytes=0, compile_s=0.0, blame=blame,
                         passes=pass_prov, sharding=self.sharding,
                         tuning=self.tuning)
        if not _ENABLED:
            return None
        _prof.inc_stat("inspect_compiles")
        si = _SigInfo(kind, sig, blame, ev)
        with _lock:
            self.compiles += 1
            if arg_names is not None:
                self.arg_names = list(arg_names)
            self.sigs[(kind, sig)] = si
            while len(self.sigs) > _MAX_SIGS:
                self.sigs.popitem(last=False)
        return _Pending(self, si)

    def record_aot(self, kind: str, example_args, compiled,
                   wall_s: float, event: Optional[dict] = None,
                   jitfn=None) -> None:
        """Register an AOT-built executable (`compile_cache.
        aot_compile`).  The real Compiled object is in hand, so
        analysis is cheap and runs immediately.  The example-arg
        structs (and the jit fn when the caller has one) are kept too,
        so hbm.plan()'s leaf classifier works on warmed programs."""
        if not _ENABLED:
            return
        from . import profiler as _prof

        sig = _sig_of_tree(example_args)
        si = _SigInfo(kind, sig, None, event)
        si.aot = True
        si.compile_wall_s = wall_s
        si._compiled = compiled
        try:
            si._structs = _to_structs(example_args)
            si._jitfn = jitfn
        except Exception:
            pass
        with _lock:
            self.aot_compiles += 1
            self.compile_wall_s += wall_s
            cur = self.sigs.setdefault((kind, sig), si)
            if cur is not si and cur._structs is None:
                cur._structs = si._structs
                cur._jitfn = jitfn
            while len(self.sigs) > _MAX_SIGS:
                self.sigs.popitem(last=False)
        _prof.inc_stat("inspect_compile_wall_us", int(wall_s * 1e6))
        if event is not None:
            event["compile_s"] = round(wall_s, 6)
        si.analyze()

    # -- inspection -------------------------------------------------------
    def latest_sig(self, kind: Optional[str] = None) -> Optional[_SigInfo]:
        with _lock:
            for (k, _), si in reversed(self.sigs.items()):
                if kind is None or k == kind:
                    return si
        return None

    def as_dict(self, analyze: bool = True) -> Dict[str, Any]:
        with _lock:
            sig_infos = list(self.sigs.values())
        d: Dict[str, Any] = {
            "name": self.name, "site": self.site,
            "n_sigs": len(sig_infos), "compiles": self.compiles,
            "aot_compiles": self.aot_compiles, "hits": self.hits,
            "compile_wall_s": round(self.compile_wall_s, 6),
            "kinds": sorted({s.kind for s in sig_infos}),
        }
        blames = [s.blame for s in sig_infos if s.blame]
        if blames:
            d["blame"] = blames
        if self.pass_report is not None:
            from . import passes as _passes

            d["passes"] = _passes.provenance_summary(self.pass_report)
        if self.sharding is not None:
            d["sharding"] = self.sharding
        if self.tuning is not None:
            d["tuning"] = self.tuning
        if self.op_profile is not None:
            d["op_profile"] = self.op_profile
        if analyze and sig_infos:
            analysis = sig_infos[-1].analyze()
            d.update({k: v for k, v in analysis.items() if k != "error"})
            if "error" in analysis:
                d["analysis_error"] = analysis["error"]
        d["signatures"] = [s.as_dict() for s in sig_infos]
        return d


# ---------------------------------------------------------------------------
# Registration / lookup
# ---------------------------------------------------------------------------

def _head_ref(symbol):
    try:
        import weakref

        return weakref.ref(symbol._outputs[0][0])
    except Exception:
        return None


def program(site: str, name: str,
            arg_names: Optional[Sequence[str]] = None,
            symbol=None, reuse: bool = False) -> ProgramRecord:
    """Get-or-create the registry record for the logical program
    ``site:name``.

    ``reuse=True`` means the caller GUARANTEES ``name`` identifies one
    logical program (gluon block names are auto-uniquified per
    process): re-registration returns the same record, so a rebuilt
    CachedOp for the same block accumulates history — which is exactly
    what makes input-structure churn blameable.

    ``reuse=False`` (symbol-derived names like ``softmax``, which any
    number of unrelated graphs share) only merges onto an existing
    record when ``symbol`` is the SAME graph (head-node identity);
    otherwise the key is uniquified with a ``#N`` suffix — two Modules
    both headed by ``softmax`` must not fabricate retrace blame
    against each other."""
    key = "%s:%s" % (site, name)
    if not _ENABLED:
        # disabled: hand back a detached record (no-op bookkeeping)
        # without polluting the registry listing
        rec = ProgramRecord(site, key)
        if arg_names is not None:
            rec.arg_names = list(arg_names)
        return rec
    head = _head_ref(symbol) if symbol is not None else None
    with _lock:
        rec = _REGISTRY.get(key)
        if rec is not None and not reuse:
            same_graph = (head is not None and rec._sym_head is not None
                          and rec._sym_head() is head()
                          and head() is not None)
            if not same_graph:
                n = 2
                while True:
                    cand = "%s#%d" % (key, n)
                    other = _REGISTRY.get(cand)
                    if other is None:
                        key, rec = cand, None
                        break
                    if (head is not None and other._sym_head is not None
                            and other._sym_head() is head()
                            and head() is not None):
                        key, rec = cand, other
                        break
                    n += 1
        if rec is None:
            rec = ProgramRecord(site, key)
            _REGISTRY[key] = rec
            while len(_REGISTRY) > _MAX_PROGRAMS:
                _REGISTRY.popitem(last=False)
        else:
            _REGISTRY.move_to_end(key)
        if arg_names is not None:
            rec.arg_names = list(arg_names)
        if head is not None:
            rec._sym_head = head
    if symbol is not None:
        # pass provenance: the registering site just built its graph
        # fns through _build_graph_fn, so the optimizer cache holds the
        # report for exactly this graph (None when passes are off)
        try:
            from . import passes as _passes

            prov = _passes.provenance_for(symbol)
            if prov is not None:
                rec.pass_report = prov
        except Exception:
            pass
    # sharding provenance: prefer what the shard pass actually stamped
    # on this graph; fall back to the plan active at registration
    try:
        if rec.sharding is None:
            if rec.pass_report is not None:
                for p in rec.pass_report.get("passes", ()):
                    if p.get("pass") == "shard" and p.get("plan"):
                        rec.sharding = p["plan"]
                        break
            if rec.sharding is None:
                from .sharding.plan import current_plan as _cur_plan

                plan = _cur_plan()
                if plan is not None:
                    rec.sharding = plan.describe()
    except Exception:
        pass
    # tuning provenance: the auto-applied `mx.tune` DB config active
    # in this process (knobs are process-global env, so every program
    # registered after the apply was built under it)
    try:
        if rec.tuning is None:
            from . import tune as _tune

            prov = _tune.current_applied()
            if prov is not None:
                rec.tuning = prov
    except Exception:
        pass
    return rec


def track_compile(record: ProgramRecord, seen_sigs: set, counter: str,
                  site: str, kind: str, sig: Tuple,
                  arg_names: Optional[Sequence[str]] = None):
    """The ONE retrace-accounting step every compile site runs per
    dispatch (Executor._track_sig, CachedOp._track_sig, FusedTrainLoop
    .run_stacked are thin wrappers that only build ``sig``).

    On a seen signature: bumps ``<counter>_hit`` and the record's hit
    count, returns None.  On a NEW signature: crosses the ``compile``
    fault-injection chokepoint (an XLA build is about to happen; flaky-
    compile recovery rides the retry policy), bumps ``<counter>_trace``,
    and returns the pending-compile token — the call site invokes
    ``tok.done(jitfn, args)`` right after the jit call so compile wall
    time and the lazy-analysis handle land in the registry.

    This is the <10us/call hot path measured by tools/check_inspect.py;
    keep it allocation-light.

    Thread-safe: serving workers share one CachedOp, so two threads
    can race the SAME new signature here.  The membership check and
    the add are one atomic section under ``_sig_lock`` — exactly one
    thread gets the compile token (the loser books a hit and rides
    jax's own once-per-signature compile internally), so N concurrent
    callers never inflate the retrace counters the CI guard
    (`tools/check_retrace.py`) bounds."""
    from . import profiler as _prof

    keyed = (kind, sig)
    with _sig_lock:
        if keyed in seen_sigs:
            fresh = False
        else:
            seen_sigs.add(keyed)
            fresh = True
    if not fresh:
        _prof.inc_stat(counter + "_hit")
        record.hit()
        return None
    from . import resilience as _res

    try:
        _res.fault_barrier("compile", site)
    except BaseException:
        # the compile never happened: un-claim the signature so a
        # caller-level retry of the whole dispatch attempts it again
        with _sig_lock:
            seen_sigs.discard(keyed)
        raise
    _prof.inc_stat(counter + "_trace")
    return record.begin_compile(kind, sig, arg_names=arg_names, site=site)


def find(name: str) -> Optional[ProgramRecord]:
    """Look up a program by exact registry name or unique substring."""
    with _lock:
        if name in _REGISTRY:
            return _REGISTRY[name]
        matches = [r for k, r in _REGISTRY.items() if name in k]
    if len(matches) == 1:
        return matches[0]
    if len(matches) > 1:
        raise MXNetError("program name %r is ambiguous: %s"
                         % (name, sorted(r.name for r in matches)))
    return None


def find_for_symbol(symbol) -> Optional[ProgramRecord]:
    """The most recently registered program bound to this Symbol
    (matched by graph head-node identity)."""
    try:
        head = symbol._outputs[0][0]
    except Exception:
        return None
    with _lock:
        records = list(_REGISTRY.values())
    for rec in reversed(records):
        ref = rec._sym_head
        if ref is not None and ref() is head:
            return rec
    return None


def programs(analyze: bool = True) -> List[Dict[str, Any]]:
    """Snapshot of every registered program (registration order).
    ``analyze=True`` (default) runs the lazy cost/memory analysis for
    each program's latest signature — may compile (see module doc)."""
    with _lock:
        records = list(_REGISTRY.values())
    return [r.as_dict(analyze=analyze) for r in records]


def analyze_all() -> int:
    """Force analysis of EVERY recorded signature (not just the latest
    per program); returns how many were analyzed.  Useful right before
    a telemetry flush so all ``compile`` events ship real figures."""
    with _lock:
        infos = [si for r in _REGISTRY.values() for si in r.sigs.values()]
    n = 0
    for si in infos:
        si.analyze()
        n += 1
    return n


def blame_summary() -> "collections.Counter":
    """Aggregated retrace culprits: blame string -> occurrence count."""
    with _lock:
        return collections.Counter(_BLAME)


def reset() -> None:
    """Drop all registry state (tests)."""
    with _lock:
        _REGISTRY.clear()
        _BLAME.clear()


def summary(analyze: bool = True) -> str:
    """Printable one-line-per-program table."""
    rows = programs(analyze=analyze)
    lines = ["%-44s %5s %5s %7s %9s %10s %10s"
             % ("program", "sigs", "comp", "hits", "wall(s)",
                "GFLOP", "peak(MB)")]
    for r in rows:
        lines.append("%-44s %5d %5d %7d %9.3f %10.3f %10.1f" % (
            r["name"][:44], r["n_sigs"],
            r["compiles"] + r["aot_compiles"], r["hits"],
            r["compile_wall_s"], r.get("flops", 0.0) / 1e9,
            r.get("peak_bytes", 0) / 2**20))
    for r in rows:
        for b in r.get("blame", []):
            lines.append("  blame[%s]: %s" % (r["name"][:40], b))
    return "\n".join(lines)


def hlo(name: str, kind: Optional[str] = None) -> str:
    """Optimized HLO text of a program's latest signature."""
    rec = find(name)
    if rec is None:
        raise MXNetError("no registered program matches %r" % name)
    si = rec.latest_sig(kind)
    if si is None:
        raise MXNetError("program %r has no %s signature"
                         % (rec.name, kind or "compiled"))
    return si.hlo_text()


# ---------------------------------------------------------------------------
# HLO histograms + per-op FLOPs (tools/hlo_report.py backend)
# ---------------------------------------------------------------------------

_DT_SIZE = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
            "s32": 4, "u32": 4, "s16": 2, "u16": 2, "pred": 1, "s8": 1,
            "u8": 1}


_STABLEHLO_RE = re.compile(
    r"=\s+(?:stablehlo|mhlo|chlo)\.(\w+)")
_STABLEHLO_RESULT_RE = re.compile(
    r"->\s*tensor<((?:\d+x)*)(\w+)>\s*$")


def _stablehlo_histogram(text: str) -> Dict[str, Any]:
    """Histogram a LOWERED (pre-optimization) StableHLO dump — the
    graph-level truth before XLA fusion/cancellation runs.  This is
    what makes layout deltas CI-checkable on CPU, where the optimized
    HLO fuses every transpose away regardless of how many the graph
    emitted (the TPU backend materializes them; see ROADMAP item 2)."""
    ops: "collections.Counter" = collections.Counter()
    convs = []
    transposes = []
    copies = 0
    for line in text.splitlines():
        m = _STABLEHLO_RE.search(line)
        if not m:
            continue
        op = m.group(1)
        ops[op] += 1
        rm = _STABLEHLO_RESULT_RE.search(line.strip())
        dtype = rm.group(2) if rm else "f32"
        shape = rm.group(1).rstrip("x").replace("x", ",") if rm else ""
        if op == "convolution":
            convs.append((dtype, shape, ""))
        elif op == "transpose":
            transposes.append((dtype, shape))
        elif op == "copy":
            copies += 1
    t_bytes = 0
    for d, shape in transposes:
        n = 1
        for dim in shape.split(","):
            if dim:
                n *= int(dim)
        t_bytes += n * _DT_SIZE.get(d, 4)
    return {
        "op_histogram_top": dict(ops.most_common(15)),
        "n_convolutions": len(convs),
        "conv_dtypes": dict(collections.Counter(d for d, _, _ in convs)),
        "convolutions": convs[:32],
        "n_transposes_surviving": len(transposes),
        "transpose_traffic_mb": round(t_bytes / 2**20, 2),
        "n_copies_surviving": copies,
        "n_fusions": 0,
        "dialect": "stablehlo",
    }


def hlo_histogram(hlo_text: str) -> Dict[str, Any]:
    """Histogram an optimized-HLO dump: op kinds, conv dtypes/shapes,
    transposes/copies that SURVIVED fusion (= materialized layout
    traffic).  Ops inside ``%fused_*`` computation bodies are excluded
    — a transpose folded into a fusion costs no extra HBM round trip;
    only top-level (entry / while-body / conditional) instructions
    materialize.

    Also accepts LOWERED StableHLO text (``jit(...).lower().as_text()``)
    and histograms the PRE-optimization graph instead — there
    ``n_transposes_surviving`` counts what the graph emitted, before
    XLA cancellation (the layout pass's graph-level feedback signal)."""
    if "stablehlo." in hlo_text or "mhlo." in hlo_text:
        return _stablehlo_histogram(hlo_text)
    ops: "collections.Counter" = collections.Counter()
    convs = []
    transposes = []
    copies = 0
    in_fusion_body = False
    for line in hlo_text.splitlines():
        s = line.strip()
        if s.endswith("{") and "(" in s:  # computation header
            cname = s.lstrip("%").split()[0]
            in_fusion_body = cname.startswith(("fused_", "%fused_")) \
                or ".fused" in cname
            continue
        if s == "}":
            in_fusion_body = False
            continue
        if in_fusion_body:
            continue
        m = re.match(r"\S+\s+=\s+(\w+)\[([\d,]*)\]\S*\s+(\S+?)\(", s)
        if not m:
            continue
        dtype, shape, op = m.group(1), m.group(2), m.group(3)
        ops[op] += 1
        if op == "convolution":
            convs.append((dtype, shape,
                          ("window=" + re.search(r"window={([^}]*)}", s)
                           .group(1)) if "window={" in s else ""))
        elif op == "transpose":
            transposes.append((dtype, shape))
        elif op == "copy":
            copies += 1
    t_bytes = 0
    for d, shape in transposes:
        n = 1
        for dim in shape.split(","):
            if dim:
                n *= int(dim)
        t_bytes += n * _DT_SIZE.get(d, 4)
    return {
        "op_histogram_top": dict(ops.most_common(15)),
        "n_convolutions": len(convs),
        "conv_dtypes": dict(collections.Counter(d for d, _, _ in convs)),
        "convolutions": convs[:32],
        "n_transposes_surviving": len(transposes),
        "transpose_traffic_mb": round(t_bytes / 2**20, 2),
        "n_copies_surviving": copies,
        "n_fusions": ops.get("fusion", 0),
    }


_OP_FLOPS_CACHE: Dict[Tuple, Optional[float]] = {}


def op_flops(node, in_shapes, in_dtypes) -> Optional[float]:
    """XLA's FLOP estimate for ONE symbol node (lower the op alone and
    read ``cost_analysis``).  Used by `visualization.print_summary`'s
    FLOPs column.  Returns None when the op cannot be lowered in
    isolation.  Memoized by (op, attrs, shapes, dtypes) — each lower
    costs ~10 ms and big models repeat the same op config dozens of
    times (a ResNet summary would otherwise stall for minutes)."""
    try:
        ck = (node.op.name, repr(sorted(node.attrs.items())),
              tuple(tuple(s) for s in in_shapes),
              tuple(str(d) for d in in_dtypes))
        if ck in _OP_FLOPS_CACHE:
            return _OP_FLOPS_CACHE[ck]
    except Exception:
        ck = None
    try:
        import functools

        import jax
        import numpy as np

        attrs = dict(node.attrs)
        if node.op.train_aware:
            attrs.setdefault("is_train", False)
        structs = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
                   for s, d in zip(in_shapes, in_dtypes)]
        fn = functools.partial(node.op.fn, **attrs)
        if node.op.needs_rng:
            key = jax.ShapeDtypeStruct((2,), np.uint32)
            lowered = jax.jit(fn).lower(key, *structs)
        else:
            lowered = jax.jit(fn).lower(*structs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out = float(ca.get("flops", 0.0) or 0.0)
    except Exception:
        out = None
    if ck is not None:
        if len(_OP_FLOPS_CACHE) > 4096:
            _OP_FLOPS_CACHE.clear()
        _OP_FLOPS_CACHE[ck] = out
    return out


def report(name_or_record=None, kind: Optional[str] = None) -> Dict[str, Any]:
    """Full inspection report for one program (default: the most
    recently registered): cost analysis, memory analysis, compile wall
    time, blame history, and the HLO op/conv/transpose/fusion
    histograms.  The backend of ``tools/hlo_report.py``."""
    if isinstance(name_or_record, ProgramRecord):
        rec = name_or_record
    elif name_or_record is None:
        with _lock:
            if not _REGISTRY:
                raise MXNetError("no programs registered yet")
            rec = next(reversed(_REGISTRY.values()))
    else:
        rec = find(name_or_record)
        if rec is None:
            raise MXNetError("no registered program matches %r"
                             % name_or_record)
    si = rec.latest_sig(kind)
    if si is None:
        raise MXNetError("program %r has no %s signature"
                         % (rec.name, kind or "compiled"))
    analysis = si.analyze()
    out: Dict[str, Any] = {
        "program": rec.name, "site": rec.site, "kind": si.kind,
        "n_sigs": len(rec.sigs), "compiles": rec.compiles,
        "aot_compiles": rec.aot_compiles, "hits": rec.hits,
        "compile_wall_s": round(si.compile_wall_s, 6),
        "signature": si.sig,
        "cost": {k: analysis.get(k) for k in
                 ("flops", "bytes_accessed", "transcendentals")},
        "memory": {k: analysis.get(k) for k in
                   ("argument_bytes", "output_bytes", "temp_bytes",
                    "alias_bytes", "peak_bytes")},
    }
    # per-class/per-layer decomposition of that peak (mx.hbm) — the
    # decode reuses the analysis just run, so this is cheap here
    try:
        from . import hbm as _hbm

        mp = _hbm.plan(rec, kind=kind)
        if "error" not in mp:
            out["memory_plan"] = mp
    except Exception:
        pass
    if "error" in analysis:
        out["analysis_error"] = analysis["error"]
    blames = [s.blame for s in rec.sigs.values() if s.blame]
    if blames:
        out["blame"] = blames
    if rec.pass_report is not None:
        out["pass_report"] = rec.pass_report
    if rec.tuning is not None:
        out["tuning"] = rec.tuning
    if rec.op_profile is not None:
        out["op_profile"] = rec.op_profile
    try:
        out.update(hlo_histogram(si.hlo_text()))
    except Exception as e:
        out["hlo_error"] = str(e)[:200]
    return out


# ---------------------------------------------------------------------------
# Device traces
# ---------------------------------------------------------------------------

class EmptyTraceError(MXNetError):
    """`trace(dir)` finished but the profiler produced no xplane file
    under the dir — the trace silently captured nothing (profiler
    already active elsewhere, a crashed plugin, an unwritable dir).
    Raised at trace exit so the caller learns NOW, not when a much
    later `mx.xprof.ingest`/TensorBoard load finds the dir empty."""


@contextlib.contextmanager
def trace(logdir: str = "/tmp/mxtpu_trace", **kwargs):
    """The supported device-trace entry point: run a block under
    ``jax.profiler`` so kernel-level device timelines land in
    ``logdir`` (open with TensorBoard's profile plugin or Perfetto,
    or feed the dir to ``mx.xprof.ingest`` for the per-op report).
    With layer attribution on (the default), trace rows and HLO op
    metadata carry the gluon/Symbol layer names::

        with mx.inspect.trace("/tmp/tb"):
            mod.forward(batch, is_train=True)

    Raises :class:`EmptyTraceError` when the profiler stopped without
    writing an ``*.xplane.pb`` under ``logdir`` (the block itself
    failing takes precedence — its exception propagates unchanged).
    """
    import jax

    jax.profiler.start_trace(logdir, **kwargs)
    ok = False
    try:
        yield logdir
        ok = True
    finally:
        jax.profiler.stop_trace()
        if ok:
            from . import xprof as _xprof

            if not _xprof.find_xplane_files(logdir):
                raise EmptyTraceError(
                    "trace produced no .xplane.pb under %r — the "
                    "profiler captured nothing (already active in "
                    "another trace? unwritable dir?)" % logdir)
