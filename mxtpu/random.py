"""Framework RNG state.

Re-design of the reference's per-device RNG
(`include/mxnet/random_generator.h`, `python/mxnet/random.py`): the
reference seeds per-device Mersenne/cuRAND states; here a single threefry
key chain feeds *stateless* XLA PRNG ops — `seed()` resets the chain, and
each random op call consumes a fresh subkey (split on the host, used on
device), so results are reproducible for a fixed seed and op sequence.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .base import getenv_int

__all__ = ["seed", "uniform", "normal", "randint", "randn", "exponential",
           "poisson", "gamma", "negative_binomial",
           "generalized_negative_binomial", "multinomial", "shuffle",
           "get_state", "set_state"]

_lock = threading.Lock()
_key = None
_seed_value = getenv_int("MXNET_TEST_SEED", 0) or None


def seed(seed_state: int, ctx=None):
    """Seed the global generator (reference `mx.random.seed`)."""
    global _key, _seed_value
    import jax

    with _lock:
        _seed_value = int(seed_state)
        _key = jax.random.PRNGKey(_seed_value)


def _next_key():
    """Split a fresh subkey off the chain (called by the imperative layer
    for every `needs_rng` op)."""
    global _key
    import jax

    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(np.random.randint(0, 2 ** 31 - 1)
                                      if _seed_value is None else _seed_value)
        _key, sub = jax.random.split(_key)
        return sub


def get_state():
    return _key


def set_state(state) -> None:
    """Restore the threefry chain captured by `get_state()`.

    Accepts the raw jax key, a numpy uint32 array, or a plain list (the
    JSON-roundtripped form `mx.checkpoint` bundles) — after restore the
    op-sequence-determinism contract of `seed()` continues from the
    captured point, so a resumed dropout-bearing training run stays
    bitwise identical to the uninterrupted one."""
    global _key
    import jax.numpy as jnp

    with _lock:
        if state is None:
            _key = None
        else:
            _key = jnp.asarray(np.asarray(state, dtype=np.uint32))


# -- convenience samplers mirroring `mx.random.*` (reference
#    python/mxnet/random.py; these route through the registered ops) ------

def _shape(shape):
    if shape is None or shape == ():
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def _invoke(name, **kwargs):
    from .ndarray.ndarray import imperative_invoke

    out = kwargs.pop("out", None)
    return imperative_invoke(name, out=out, **kwargs)[0]


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return _invoke("_random_uniform", low=float(low), high=float(high),
                   shape=_shape(shape), dtype=dtype, ctx=ctx, out=out)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return _invoke("_random_normal", loc=float(loc), scale=float(scale),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def randn(*shape, dtype="float32", ctx=None):
    return normal(0.0, 1.0, shape=tuple(shape) or (1,), dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=(1,), dtype="int32", ctx=None, out=None):
    if high is None:
        low, high = 0, low
    return _invoke("_random_randint", low=int(low), high=int(high),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def exponential(scale=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return _invoke("_random_exponential", lam=1.0 / float(scale),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def poisson(lam=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return _invoke("_random_poisson", lam=float(lam),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return _invoke("_random_gamma", alpha=float(alpha), beta=float(beta),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def negative_binomial(k=1, p=1.0, shape=(), dtype="float32", ctx=None,
                      out=None):
    return _invoke("_random_negative_binomial", k=int(k), p=float(p),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(), dtype="float32",
                                  ctx=None, out=None):
    return _invoke("_random_generalized_negative_binomial", mu=float(mu),
                   alpha=float(alpha),
                   shape=_shape(shape),
                   dtype=dtype, ctx=ctx, out=out)


def multinomial(data, shape=(), get_prob=False, dtype="int32", out=None):
    from .ndarray.ndarray import imperative_invoke

    res = imperative_invoke("_sample_multinomial", data,
                            shape=shape if shape else 1, get_prob=get_prob,
                            dtype=dtype, out=out)
    return res if get_prob else res[0]


def shuffle(data, out=None):
    from .ndarray.ndarray import imperative_invoke

    return imperative_invoke("_shuffle", data, out=out)[0]
