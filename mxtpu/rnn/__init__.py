"""`mx.rnn` — symbolic RNN cell API (reference: `python/mxnet/rnn/`)."""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, DropoutCell,
                       BidirectionalCell)
from .io import BucketSentenceIter

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "BidirectionalCell", "BucketSentenceIter"]
