"""BucketSentenceIter (reference: `python/mxnet/rnn/io.py`).

Buckets variable-length token sequences into fixed-length padded
batches; each DataBatch carries its `bucket_key` so BucketingModule can
switch executors (one compiled XLA module per bucket length).
"""
from __future__ import annotations

import random as pyrandom
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import ndarray as nd_mod

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
        buckets = sorted(buckets)
        ndiscard = 0
        self.data: List[List] = [[] for _ in buckets]
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype).reshape(-1, blen)
                     for x, blen in zip(self.data, buckets)]
        if ndiscard:
            import logging

            logging.warning("discarded %d sentences longer than the "
                            "largest bucket", ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.layout = layout
        self.default_bucket_key = max(buckets)
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1,
                                   batch_size)])
        self.curr_idx = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key))]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self.curr_idx = 0
        pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        # label = input shifted one step left (next-token prediction)
        self.ndlabel = []
        self.nddata = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self) -> DataBatch:
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        L = self.buckets[i]
        return DataBatch(data=[nd_mod.array(data)],
                         label=[nd_mod.array(label)],
                         bucket_key=L,
                         provide_data=[DataDesc(self.data_name,
                                                (self.batch_size, L))],
                         provide_label=[DataDesc(self.label_name,
                                                 (self.batch_size, L))])
