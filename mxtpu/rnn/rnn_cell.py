"""Symbolic RNN cells (reference: `python/mxnet/rnn/rnn_cell.py`).

BaseRNNCell/RNNCell/LSTMCell/GRUCell/SequentialRNNCell/DropoutCell plus
FusedRNNCell wrapping the fused `RNN` op (`src/operator/rnn.cc` analog in
`mxtpu/ops/rnn_op.py`).  `unroll` builds the time-unrolled symbolic
graph; on TPU the whole unrolled graph compiles to one XLA module, so
explicit unrolling costs only compile time (the fused cell lowers to a
`lax.scan`).

Deviation from the reference: symbolic `begin_state` needs an explicit
`batch_size` (the reference uses 0-as-unknown shape inference; here
shapes are concrete at bind time — BucketingModule passes it per bucket).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..base import MXNetError
from .. import symbol as sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "BidirectionalCell"]


class RNNParams(object):
    """Lazily-created shared weight container (reference
    `rnn_cell.py:RNNParams`)."""

    def __init__(self, prefix: str = ""):
        self._prefix = prefix
        self._params: Dict[str, Any] = {}

    def get(self, name: str, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    def __init__(self, prefix: str = "", params: Optional[RNNParams] = None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self) -> RNNParams:
        self._own_params = False
        return self._params

    @property
    def state_info(self) -> List[Dict]:
        raise NotImplementedError

    @property
    def _gate_names(self) -> Tuple[str, ...]:
        return ()

    def __call__(self, inputs, states):
        raise NotImplementedError

    def begin_state(self, func=None, batch_size: int = 0, **kwargs):
        """Initial zero states; `batch_size` required symbolically."""
        if self._modified:
            raise MXNetError("cannot begin_state on a modified cell")
        if func is None:
            func = sym.zeros
        states = []
        for info in self.state_info:
            self._init_counter += 1
            shape = tuple(batch_size if d == 0 else d
                          for d in info["shape"])
            states.append(func(name="%sbegin_state_%d"
                               % (self._prefix, self._init_counter),
                               shape=shape, **kwargs))
        return states

    def unroll(self, length: int, inputs, begin_state=None,
               layout: str = "NTC", merge_outputs: Optional[bool] = None,
               batch_size: int = 0):
        """Unroll the cell `length` steps (reference
        `rnn_cell.py:BaseRNNCell.unroll`)."""
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            inputs = list(sym.split_v2(inputs, length, axis=axis,
                                       squeeze_axis=True)) if hasattr(
                sym, "split_v2") else list(
                sym.SliceChannel(inputs, num_outputs=length, axis=axis,
                                 squeeze_axis=True))
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(inputs[i], states)
            outputs.append(out)
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla tanh RNN cell."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        output = sym.Activation(data=i2h + h2h, act_type=self._activation,
                                name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference `rnn_cell.py:LSTMCell`)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias

        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        slices = sym.SliceChannel(gates, num_outputs=4, axis=1,
                                  name="%sslice" % name)
        in_gate = sym.Activation(slices[0], act_type="sigmoid")
        forget_gate = sym.Activation(slices[1], act_type="sigmoid")
        in_transform = sym.Activation(slices[2], act_type="tanh")
        out_gate = sym.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym.Activation(next_c, act_type="tanh",
                                           name="%sstate" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference `rnn_cell.py:GRUCell`)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=prev_h, weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%sh2h" % name)
        i2h_s = sym.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = sym.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = sym.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = sym.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        cand = sym.Activation(i2h_s[2] + reset * h2h_s[2], act_type="tanh")
        next_h = (1.0 - update) * cand + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Wraps the fused `RNN` op — one lax.scan over the sequence
    (reference FusedRNNCell → cuDNN RNN)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._param = self.params.get("parameters")

    @property
    def state_info(self):
        d = 2 if self._bidirectional else 1
        info = [{"shape": (self._num_layers * d, 0, self._num_hidden)}]
        if self._mode == "lstm":
            info.append({"shape": (self._num_layers * d, 0,
                                   self._num_hidden)})
        return info

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, batch_size: int = 0):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            inputs = sym.stack(*inputs, axis=1 if layout == "NTC" else 0)
        if layout == "NTC":  # RNN op wants TNC
            inputs = sym.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        rnn_args = [inputs, self._param] + list(begin_state)
        out = sym.RNN(*rnn_args, state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=False,
                      name="%srnn" % self._prefix)
        if layout == "NTC":
            out = sym.SwapAxis(out, dim1=0, dim2=1)
        if merge_outputs is False:
            axis = layout.find("T")
            out = list(sym.SliceChannel(out, num_outputs=length,
                                        axis=axis, squeeze_axis=True))
        return out, []


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in sequence."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells: List[BaseRNNCell] = []

    def add(self, cell: BaseRNNCell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, func=None, batch_size: int = 0, **kwargs):
        return sum([c.begin_state(func=func, batch_size=batch_size,
                                  **kwargs) for c in self._cells], [])

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    def __init__(self, dropout: float, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(data=inputs, p=self._dropout)
        return inputs, states


class BidirectionalCell(BaseRNNCell):
    """Runs two cells over the sequence in opposite directions and
    concatenates outputs (unroll-only, like the reference)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._l_cell, self._r_cell = l_cell, r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, func=None, batch_size: int = 0, **kwargs):
        return (self._l_cell.begin_state(func=func, batch_size=batch_size,
                                         **kwargs) +
                self._r_cell.begin_state(func=func, batch_size=batch_size,
                                         **kwargs))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell supports unroll() only")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, batch_size: int = 0):
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            inputs = list(sym.SliceChannel(inputs, num_outputs=length,
                                           axis=axis, squeeze_axis=True))
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        nl = len(self._l_cell.state_info)
        l_out, l_states = self._l_cell.unroll(
            length, inputs, begin_state[:nl], layout=layout,
            merge_outputs=False)
        r_out, r_states = self._r_cell.unroll(
            length, list(reversed(inputs)), begin_state[nl:],
            layout=layout, merge_outputs=False)
        outputs = [sym.Concat(l, r, dim=1, name="%st%d" %
                              (self._output_prefix, i))
                   for i, (l, r) in enumerate(zip(l_out,
                                                  reversed(r_out)))]
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
