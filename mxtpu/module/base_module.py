"""BaseModule — the high-level train/predict interface.

Reference: `python/mxnet/module/base_module.py` — `fit` (:410) drives
epochs of forward_backward/update/update_metric with callbacks; `score`
(:213), `predict` (:320), `iter_predict` (:275).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from ..base import MXNetError
from .. import metric as metric_mod
from ..model import BatchEndParam
from ..ndarray import ndarray as nd_mod
from ..ndarray.ndarray import NDArray

__all__ = ["BaseModule"]


def _as_metric(eval_metric):
    if isinstance(eval_metric, metric_mod.EvalMetric):
        return eval_metric
    return metric_mod.create(eval_metric)


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, (list, tuple)) else [x]


class BaseModule(object):
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- properties subclasses provide -------------------------------------
    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    @property
    def symbol(self):
        return self._symbol

    # -- abstract core ------------------------------------------------------
    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- composed helpers ----------------------------------------------------
    def forward_backward(self, data_batch):
        """One fused fwd+bwd (reference `base_module.py:194`)."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0, sparse_row_id_fn=None):
        """Evaluate on eval_data (reference `base_module.py:213`)."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.prepare(eval_batch, sparse_row_id_fn=sparse_row_id_fn)
            self.forward(eval_batch, is_train=False)
            if isinstance(eval_batch, list):
                self.update_metric(eval_metric,
                                   [eb.label for eb in eval_batch],
                                   pre_sliced=True)
            else:
                self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                bep = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                    eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(bep)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True,
                     sparse_row_id_fn=None):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.prepare(eval_batch, sparse_row_id_fn=sparse_row_id_fn)
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outputs = [out[0:out.shape[0] - pad]
                       for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """Run inference, concatenating batch outputs (reference
        `base_module.py:320`)."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        if reset:
            eval_data.reset()
        output_list: List[List[NDArray]] = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.prepare(eval_batch, sparse_row_id_fn=sparse_row_id_fn)
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError("output count varies across batches")
            output_list2 = [
                nd_mod.concat(*[out[i] for out in output_list], dim=0)
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The training loop (reference `base_module.py:410`)."""
        from ..initializer import Uniform

        if num_epoch is None:
            raise MXNetError("num_epoch required for fit")
        if initializer is None:
            initializer = Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params)
                            if not isinstance(optimizer_params, dict)
                            else optimizer_params, force_init=force_init)
        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            end_of_batch = False
            data_iter = iter(train_data)
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                if isinstance(data_batch, list):
                    self.update_metric(eval_metric,
                                       [db.label for db in data_batch],
                                       pre_sliced=True)
                else:
                    self.update_metric(eval_metric, data_batch.label)
                try:
                    next_data_batch = next(data_iter)
                    self.prepare(next_data_batch,
                                 sparse_row_id_fn=sparse_row_id_fn)
                except StopIteration:
                    end_of_batch = True
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    bep = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                        eval_metric=eval_metric,
                                        locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(bep)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p, allow_missing=False,
                            force_init=True, allow_extra=False)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    # -- misc ----------------------------------------------------------------
    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Row-sparse pull hook before forward (reference
        `base_module.py:180`); default no-op."""

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError
